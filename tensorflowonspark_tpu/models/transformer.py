"""Decoder-only transformer LM with mesh-parallel attention.

The reference framework predates attention entirely (SURVEY §5.7); this model
is the long-context showcase of the TPU-native design: the same module runs

- ``attention="full"``     — plain causal attention (single device / small S),
- ``attention="flash"``    — the pallas FlashAttention-2 kernels
  (:mod:`tensorflowonspark_tpu.ops.flash_attention`): memory-linear in S,
  hand-scheduled VMEM traffic on TPU, interpret mode elsewhere,
- ``attention="ring"``     — ring attention over the mesh's ``"seq"`` axis
  (sequence parallelism; see :mod:`tensorflowonspark_tpu.parallel.ring`),
- ``attention="ulysses"``  — all-to-all head-parallel attention.

Everything is static-shaped and bf16-friendly; the attention choice only
swaps the core contraction, so checkpoints are interchangeable between modes
(e.g. train with ring on a pod, serve with full on one chip).
"""

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from tensorflowonspark_tpu.models import register_model
from tensorflowonspark_tpu.parallel import ring


class Attention(nn.Module):
    num_heads: int
    head_dim: int
    attention: str = "full"   # full | flash | ring | ulysses
    mesh: Optional[object] = None
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        features = self.num_heads * self.head_dim
        qkv = nn.DenseGeneral((3, self.num_heads, self.head_dim),
                              dtype=self.dtype, name="qkv")(x)
        q, k, v = (qkv[:, :, i] for i in range(3))
        if self.attention == "flash":
            from tensorflowonspark_tpu.ops import flash_attention

            out = flash_attention(q, k, v, causal=True)
        elif self.attention == "ring":
            assert self.mesh is not None, "ring attention needs a mesh"
            out = ring.ring_attention(q, k, v, self.mesh, causal=True)
        elif self.attention == "ulysses":
            assert self.mesh is not None, "ulysses attention needs a mesh"
            out = ring.ulysses_attention(q, k, v, self.mesh, causal=True)
        else:
            out = ring.reference_attention(q, k, v, causal=True)
        out = out.reshape(out.shape[0], out.shape[1], features)
        return nn.Dense(x.shape[-1], dtype=self.dtype, name="proj")(out)


class _RouterParams(nn.Module):
    """Router weights with ``nn.Dense``'s exact param layout
    (``{kernel, bias}``) but returned raw instead of applied — the
    shard_map EP path routes inside the mapped body
    (:func:`~tensorflowonspark_tpu.parallel.ep.moe_ffn`), so it needs the
    values, while checkpoints must stay interchangeable with the
    ``ep_mode="gspmd"`` layer that applies a real Dense."""

    in_dim: int
    features: int

    @nn.compact
    def __call__(self):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (self.in_dim, self.features))
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        return kernel, bias


class MoEMlp(nn.Module):
    """Switch-style top-1 mixture-of-experts FFN (GShard dispatch/combine).

    Built the TPU way: routing is expressed as dense one-hot einsums (no
    gathers, no dynamic shapes), so the whole layer is three batched
    matmuls on the MXU; capacity-overflowed tokens contribute zero and ride
    the block's residual.  Routing is **grouped per batch row** (the
    GShard/Switch group trick): capacity and the dispatch/combine tensors
    scale with the sequence length, not the global token count, keeping
    dispatch cost linear in batch.

    Expert parallelism: shard the experts' leading dim over the mesh's
    ``expert`` axis —

        tp_param_shardings(params, mesh, axis="expert",
                           rules=[("moe/(w1|w2|b1|b2)", 0), ("", None)])

    (the ``("", None)`` catch-all keeps every non-expert param replicated
    on that axis) — and XLA turns the dispatch/combine einsums into the
    all-to-alls of expert parallelism.

    The load-balance auxiliary (Switch Transformer eq. 4) is sown under
    ``intermediates/moe_aux_loss``; ``loss_fn`` folds it in when present.
    """

    num_experts: int = 8
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    # "gspmd": dense one-hot einsums, XLA partitions them into all-to-alls
    # when params/mesh carry the expert axis (zero model coupling to the
    # mesh).  "shard_map": the explicit DeepSpeed-MoE schedule
    # (parallel/ep.moe_ffn) — identical math (equality-tested), same
    # checkpoint layout, but the collectives are written out; requires
    # ``mesh`` with an ``expert`` axis and the group dim sharded over it.
    ep_mode: str = "gspmd"
    mesh: Optional[object] = None
    # shard_map mode only: mesh axes the caller's batch sharding puts on the
    # group dim (e.g. ("data", "fsdp", "expert")); the EP kernel keeps the
    # batch partitioned over them instead of all-gathering it onto every
    # expert shard.  None = ("expert",) (pure EP).
    ep_batch_axes: Optional[tuple] = None
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        import jax

        batch, seq, d_model = x.shape                            # groups = rows
        hidden = d_model * self.mlp_ratio
        e = self.num_experts
        capacity = max(int(self.capacity_factor * seq / e), 1)

        # router in fp32: tiny matmul, and routing decisions should not
        # flip with the compute dtype
        if self.ep_mode == "shard_map":
            from tensorflowonspark_tpu.parallel import ep as ep_mod

            assert self.mesh is not None, "ep_mode=shard_map needs a mesh"
            # Declare the SAME param tree nn.Dense would (checkpoints stay
            # interchangeable with ep_mode="gspmd"), but hand the raw
            # values to the explicit-EP kernel instead of applying a
            # submodule.
            router = _RouterParams(d_model, e, name="router")
            rk, rb = router()
            w1 = self.param("w1", nn.initializers.lecun_normal(),
                            (e, d_model, hidden))
            b1 = self.param("b1", nn.initializers.zeros, (e, hidden))
            w2 = self.param("w2", nn.initializers.lecun_normal(),
                            (e, hidden, d_model))
            b2 = self.param("b2", nn.initializers.zeros, (e, d_model))
            y, aux = ep_mod.moe_ffn(
                x, {"router": {"kernel": rk, "bias": rb},
                    "w1": w1, "b1": b1, "w2": w2, "b2": b2},
                self.mesh, e, capacity_factor=self.capacity_factor,
                dtype=self.dtype, batch_axes=self.ep_batch_axes)
            self.sow("intermediates", "moe_aux_loss", aux)
            return y

        logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            x.astype(jnp.float32))                               # [G, S, E]
        probs = jax.nn.softmax(logits, axis=-1)
        expert_idx = jnp.argmax(probs, axis=-1)                  # [G, S]
        expert_prob = jnp.max(probs, axis=-1)                    # [G, S]
        expert_onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.int32)

        # per-group position of each token in its expert's buffer, in int32
        # (a low-precision cumsum would saturate and collide slots);
        # beyond-capacity tokens are dropped and ride the residual
        pos = jnp.cumsum(expert_onehot, axis=1) * expert_onehot  # [G, S, E]
        pos = pos.sum(axis=-1) - 1                               # [G, S]
        keep = (pos < capacity).astype(x.dtype)
        pos_onehot = jax.nn.one_hot(pos, capacity, dtype=x.dtype)
        dispatch = (expert_onehot.astype(x.dtype)
                    * keep[..., None])[..., None] \
            * pos_onehot[:, :, None, :]                          # [G, S, E, C]

        w1 = self.param("w1", nn.initializers.lecun_normal(),
                        (e, d_model, hidden))
        b1 = self.param("b1", nn.initializers.zeros, (e, hidden))
        w2 = self.param("w2", nn.initializers.lecun_normal(),
                        (e, hidden, d_model))
        b2 = self.param("b2", nn.initializers.zeros, (e, d_model))

        expert_in = jnp.einsum("gsec,gsd->gecd", dispatch, x)    # [G, E, C, D]
        h = jnp.einsum("gecd,edh->gech", expert_in,
                       w1.astype(self.dtype)) + b1.astype(self.dtype)[:, None]
        h = nn.gelu(h)
        out = jnp.einsum("gech,ehd->gecd", h,
                         w2.astype(self.dtype)) + b2.astype(self.dtype)[:, None]
        combine = dispatch * expert_prob.astype(x.dtype)[..., None, None]
        mixed = jnp.einsum("gsec,gecd->gsd", combine, out)       # [G, S, D]

        # Switch load-balance loss: E * sum_e fraction_e * mean_prob_e
        fraction = expert_onehot.astype(jnp.float32).mean(axis=(0, 1))
        mean_prob = probs.mean(axis=(0, 1))
        self.sow("intermediates", "moe_aux_loss",
                 e * jnp.sum(fraction * mean_prob))
        return mixed


class Block(nn.Module):
    num_heads: int
    head_dim: int
    mlp_ratio: int = 4
    attention: str = "full"
    mlp: str = "dense"        # dense | moe
    num_experts: int = 8
    capacity_factor: float = 1.25
    ep_mode: str = "gspmd"    # gspmd | shard_map (see MoEMlp)
    mesh: Optional[object] = None
    ep_batch_axes: Optional[tuple] = None
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + Attention(self.num_heads, self.head_dim, self.attention,
                          self.mesh, self.dtype)(h)
        h = nn.LayerNorm(dtype=self.dtype)(x)
        if self.mlp == "moe":
            h = MoEMlp(num_experts=self.num_experts,
                       mlp_ratio=self.mlp_ratio,
                       capacity_factor=self.capacity_factor,
                       ep_mode=self.ep_mode, mesh=self.mesh,
                       ep_batch_axes=self.ep_batch_axes,
                       dtype=self.dtype, name="moe")(h)
        else:
            h = nn.Dense(x.shape[-1] * self.mlp_ratio, dtype=self.dtype)(h)
            h = nn.gelu(h)
            h = nn.Dense(x.shape[-1], dtype=self.dtype)(h)
        return x + h


class TransformerLM(nn.Module):
    vocab_size: int = 32000
    num_layers: int = 4
    num_heads: int = 8
    head_dim: int = 64
    max_seq_len: int = 2048
    attention: str = "full"
    mlp: str = "dense"        # dense | moe
    num_experts: int = 8
    capacity_factor: float = 1.25
    ep_mode: str = "gspmd"    # gspmd | shard_map (see MoEMlp)
    mesh: Optional[object] = None
    ep_batch_axes: Optional[tuple] = None
    remat: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens):
        d_model = self.num_heads * self.head_dim
        x = nn.Embed(self.vocab_size, d_model, dtype=self.dtype,
                     name="embed")(tokens)
        pos = nn.Embed(self.max_seq_len, d_model, dtype=self.dtype,
                       name="pos_embed")(jnp.arange(tokens.shape[1]))
        x = x + pos[None]
        # remat trades FLOPs for HBM: each block's activations (incl. the
        # full-attention S x S probs the backward pass would otherwise
        # keep per layer) are recomputed during backprop instead of
        # stored — the standard TPU recipe for configs whose stored
        # activations exceed HBM (e.g. d2048 x 16L x b16 full attention).
        block_cls = nn.remat(Block) if self.remat else Block
        for i in range(self.num_layers):
            x = block_cls(self.num_heads, self.head_dim,
                          attention=self.attention, mlp=self.mlp,
                          num_experts=self.num_experts,
                          capacity_factor=self.capacity_factor,
                          ep_mode=self.ep_mode, mesh=self.mesh,
                          ep_batch_axes=self.ep_batch_axes,
                          dtype=self.dtype, name="block_%d" % i)(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        # weight-tied readout keeps the big vocab matmul on the MXU once
        embed = self.variables["params"]["embed"]["embedding"]
        return (x @ embed.T.astype(self.dtype)).astype(jnp.float32)


@register_model("transformer_lm")
def build_transformer(vocab_size=32000, num_layers=4, num_heads=8,
                      head_dim=64, max_seq_len=2048, attention="full",
                      mlp="dense", num_experts=8, capacity_factor=1.25,
                      ep_mode="gspmd", mesh=None, ep_batch_axes=None,
                      remat=False, dtype="float32"):
    return TransformerLM(vocab_size=vocab_size, num_layers=num_layers,
                         num_heads=num_heads, head_dim=head_dim,
                         max_seq_len=max_seq_len, attention=attention,
                         mlp=mlp, num_experts=num_experts,
                         capacity_factor=capacity_factor, ep_mode=ep_mode,
                         mesh=mesh, ep_batch_axes=ep_batch_axes,
                         remat=remat, dtype=jnp.dtype(dtype))


def _sum_moe_aux(tree):
    """Sum every ``moe_aux_loss`` sown anywhere in the intermediates tree;
    None when the model has no MoE layers."""
    total, found = 0.0, False
    if isinstance(tree, dict):
        for key, val in tree.items():
            if key == "moe_aux_loss":
                for v in (val if isinstance(val, (tuple, list)) else (val,)):
                    total = total + v
                    found = True
            else:
                sub = _sum_moe_aux(val)
                if sub is not None:
                    total = total + sub
                    found = True
    return total if found else None


def loss_fn(model, moe_aux_weight=0.01):
    """Next-token cross-entropy with per-row masking.

    The model is applied to the *full* sequence (not ``tokens[:, :-1]``) so
    the sequence length stays divisible by the mesh's ``seq`` axis for
    ring/ulysses attention; the last position, which has no target, is
    excluded via a position mask instead.

    MoE models' sown load-balance auxiliaries are folded in with weight
    ``moe_aux_weight`` (Switch Transformer's alpha=0.01 default) and
    reported via ``aux["moe_aux_loss"]``.
    """
    import optax

    def loss(params, batch, mask):
        tokens = batch["tokens"].astype(jnp.int32)
        logits, state = model.apply({"params": params}, tokens,
                                    mutable=["intermediates"])   # [B, S, V]
        targets = jnp.roll(tokens, -1, axis=1)                # last pos junk
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        pos_mask = jnp.ones(tokens.shape[1]).at[-1].set(0.0)  # drop last pos
        ce = (ce * pos_mask[None]).sum(axis=-1) / pos_mask.sum()
        ce = (ce * mask).sum() / jnp.maximum(mask.sum(), 1.0)
        aux = {}
        lb = _sum_moe_aux(dict(state.get("intermediates", {})))
        if lb is not None:
            aux["moe_aux_loss"] = lb
            ce = ce + moe_aux_weight * lb
        return ce, aux

    return loss
