"""ResNet family: ResNet50-v1.5 (ImageNet) and ResNet56 (CIFAR-10).

Capability parity with the reference's resnet example models
(``examples/resnet/resnet_model.py`` — ResNet50 v1.5 with the stride-2 in the
3x3 of each bottleneck; ``examples/resnet/resnet_cifar_model.py`` — the
6n+2-layer CIFAR ResNet with basic blocks), rebuilt in flax for TPU:

- NHWC layouts and bf16 compute dtype keep convs on the MXU;
- BatchNorm carries explicit ``batch_stats`` collections (functional state);
- no data-dependent Python control flow — the whole model jits statically.
"""

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from tensorflowonspark_tpu.models import register_model

ModuleDef = Any


def space_to_depth(x, block=2):
    """NHWC space-to-depth: ``(B, H, W, C) -> (B, H/b, W/b, C*b*b)``.

    Pixel ``(bh*b+i, bw*b+j, c)`` lands in channel ``(i*b + j)*C + c`` of
    block ``(bh, bw)`` — the layout :func:`s2d_stem_kernel` assumes."""
    b_, h, w, c = x.shape
    if h % block or w % block:
        raise ValueError(
            "stem='s2d' requires H and W divisible by {} (got {}x{}); use "
            "stem='conv7' or pad/resize the input".format(block, h, w))
    x = x.reshape(b_, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b_, h // block, w // block, c * block * block)


def s2d_stem_kernel(kernel7):
    """Transform a ``(7, 7, C, F)`` stride-2 SAME stem kernel into the
    exactly-equivalent ``(4, 4, C*4, F)`` kernel for a stride-1 conv over
    :func:`space_to_depth` (block 2) input with padding ``((1, 2), (1, 2))``.

    SAME/stride-2/k=7 taps input ``[2i-2, 2i+4]`` — an even start — so
    zero-padding the kernel to 8x8 at the bottom/right keeps every tap's
    block alignment and each 2x2 pixel block folds into the s2d channel
    dim.  Used by tests to prove equivalence and by converters migrating
    conv7 checkpoints to s2d models."""
    import numpy as np

    k = np.asarray(kernel7)
    kh, kw, c, f = k.shape
    assert (kh, kw) == (7, 7), (kh, kw)
    k = np.pad(k, ((0, 1), (0, 1), (0, 0), (0, 0)))  # 8x8, zeros bottom/right
    # (4, 2, 4, 2, C, F): split each spatial dim into (block_index, offset)
    k = k.reshape(4, 2, 4, 2, c, f)
    # s2d channel order is (off_h, off_w, c) -> fold offsets over channels
    k = k.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 2 * 2 * c, f)
    return k


class BottleneckBlock(nn.Module):
    """ResNet-v1.5 bottleneck: 1x1 -> 3x3(stride) -> 1x1 with projection
    shortcut (stride placement per reference ``resnet_model.py`` v1.5)."""

    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1), use_bias=False)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), strides=(self.strides,) * 2,
                      use_bias=False)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1), use_bias=False)(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 strides=(self.strides,) * 2,
                                 use_bias=False)(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    """CIFAR basic block (two 3x3 convs; reference ``resnet_cifar_model.py``)."""

    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides,) * 2,
                      use_bias=False)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), use_bias=False)(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1),
                                 strides=(self.strides,) * 2,
                                 use_bias=False)(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """Configurable ResNet.

    ``stage_sizes``/``block_cls`` select the variant: [3,4,6,3] bottleneck =
    ResNet50 v1.5; [9,9,9] basic = ResNet56 for CIFAR.
    """

    stage_sizes: Sequence[int]
    block_cls: type = BottleneckBlock
    num_classes: int = 1000
    num_filters: int = 64
    cifar_stem: bool = False   # 3x3 stem, no max-pool (CIFAR variant)
    stem: str = "conv7"        # "conv7" | "s2d" (space-to-depth, TPU-fast)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        conv = partial(nn.Conv, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        x = x.astype(self.dtype)
        if self.cifar_stem:
            x = conv(self.num_filters, (3, 3), use_bias=False)(x)
            x = norm()(x)
            x = nn.relu(x)
        else:
            if self.stem not in ("conv7", "s2d"):
                raise ValueError(
                    "unknown stem {!r}; expected 'conv7' or 's2d'".format(
                        self.stem))
            if self.stem == "s2d":
                # Space-to-depth stem: a 7x7/s2 conv on 3 channels starves
                # the MXU (channels pad 3->8); the exactly-equivalent 4x4/s1
                # conv on the (H/2, W/2, 4C) space-to-depth input keeps it
                # fed (kernel mapping: s2d_stem_kernel).
                x = space_to_depth(x, 2)
                x = conv(self.num_filters, (4, 4),
                         padding=((1, 2), (1, 2)), use_bias=False)(x)
            else:
                x = conv(self.num_filters, (7, 7), strides=(2, 2),
                         use_bias=False)(x)
            x = norm()(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(self.num_filters * 2 ** i,
                                   strides=strides, conv=conv, norm=norm)(x)
        x = x.mean(axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


@register_model("resnet50")
def build_resnet50(num_classes=1000, dtype="bfloat16", blocks_per_stage=None,
                  stem="conv7"):
    """ResNet50 v1.5 for ImageNet (reference ``resnet_imagenet_main.py``).

    ``blocks_per_stage`` is the size knob (the reference's ``resnet_size``):
    None = the [3,4,6,3] ResNet-50; N = [N,N,N,N] bottleneck stages.  Part
    of the registry signature so exports of custom-depth models rebuild
    correctly from their descriptor.  ``stem="s2d"`` selects the
    space-to-depth stem (exactly equivalent math, MXU-friendly)."""
    stage_sizes = ([blocks_per_stage] * 4 if blocks_per_stage
                   else [3, 4, 6, 3])
    return ResNet(stage_sizes=stage_sizes, block_cls=BottleneckBlock,
                  num_classes=num_classes, stem=stem, dtype=jnp.dtype(dtype))


@register_model("resnet56_cifar")
def build_resnet56(num_classes=10, dtype="float32", blocks_per_stage=9):
    """ResNet56 for CIFAR-10 (reference ``resnet_cifar_main.py``).

    ``blocks_per_stage``: 6n+2 layers; 9 = ResNet-56 (size knob in the
    registry signature so custom-depth exports rebuild correctly)."""
    return ResNet(stage_sizes=[blocks_per_stage] * 3, block_cls=BasicBlock,
                  num_classes=num_classes, num_filters=16, cifar_stem=True,
                  dtype=jnp.dtype(dtype))


def loss_fn(model, weight_decay=0.0, label_smoothing=0.0):
    """Masked cross-entropy (+L2) for the Trainer's extra-state contract:
    ``loss(params, batch_stats, batch, mask)``; updated BatchNorm statistics
    return via ``aux["extra_state"]`` (never optimized)."""
    import jax
    import optax

    def loss(params, batch_stats, batch, mask):
        logits, new_state = model.apply(
            {"params": params, "batch_stats": batch_stats},
            batch["image"], train=True, mutable=["batch_stats"])
        labels = batch["label"].astype(jnp.int32)
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels) if label_smoothing == 0.0 else \
            optax.softmax_cross_entropy(
                logits, optax.smooth_labels(
                    jax.nn.one_hot(labels, logits.shape[-1]),
                    label_smoothing))
        ce = (ce * mask).sum() / jnp.maximum(mask.sum(), 1.0)
        if weight_decay:
            l2 = sum(jnp.sum(p ** 2) for p in
                     jax.tree_util.tree_leaves(params) if p.ndim > 1)
            ce = ce + weight_decay * l2
        acc = (((logits.argmax(-1) == labels) * mask).sum()
               / jnp.maximum(mask.sum(), 1.0))
        return ce, {"accuracy": acc, "extra_state": new_state["batch_stats"]}

    return loss
