"""U-Net for semantic segmentation (reference ``examples/segmentation``).

The reference's segmentation example is a MobileNetV2-encoder + pix2pix-
upsampler U-Net over oxford_iiit_pet producing 3-class masks
(``segmentation_spark.py:70-122``).  This is the same shape of model — a
strided-conv encoder with skip connections and transpose-conv upsampling —
built conv-first for the MXU (NHWC, bf16-capable, static shapes).
"""

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from tensorflowonspark_tpu.models import register_model


class DownBlock(nn.Module):
    filters: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.filters, (3, 3), strides=(2, 2), use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.GroupNorm(num_groups=min(8, self.filters), dtype=self.dtype)(x)
        return nn.relu(x)


class UpBlock(nn.Module):
    """Transpose-conv upsampler (the reference's pix2pix.upsample,
    ``segmentation_spark.py:100-110``)."""

    filters: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, skip):
        x = nn.ConvTranspose(self.filters, (3, 3), strides=(2, 2),
                             use_bias=False, dtype=self.dtype)(x)
        x = nn.GroupNorm(num_groups=min(8, self.filters), dtype=self.dtype)(x)
        x = nn.relu(x)
        return jnp.concatenate([x, skip], axis=-1)


class UNet(nn.Module):
    """Encoder/decoder with skip connections; output: per-pixel class logits."""

    num_classes: int = 3
    encoder_filters: Sequence[int] = (32, 64, 128, 256)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(16, (3, 3), dtype=self.dtype)(x)
        skips = []
        for f in self.encoder_filters:
            skips.append(x)
            x = DownBlock(f, dtype=self.dtype)(x)
        for f, skip in zip(reversed(self.encoder_filters[:-1]),
                           reversed(skips[1:])):
            x = UpBlock(f, dtype=self.dtype)(x, skip)
        x = UpBlock(16, dtype=self.dtype)(x, skips[0])
        # final per-pixel classifier in fp32 for stable softmax
        return nn.Conv(self.num_classes, (1, 1), dtype=jnp.float32)(x)


@register_model("unet")
def build_unet(num_classes=3, dtype="float32", encoder_filters=(32, 64, 128, 256)):
    return UNet(num_classes=num_classes, dtype=jnp.dtype(dtype),
                encoder_filters=tuple(encoder_filters))


def loss_fn(model):
    """Masked per-pixel cross-entropy (mask is per-row from the infeed)."""
    import optax

    def loss(params, batch, mask):
        logits = model.apply({"params": params}, batch["image"])
        labels = batch["mask"].astype(jnp.int32)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        ce = ce.mean(axis=(1, 2))  # per-example
        ce = (ce * mask).sum() / jnp.maximum(mask.sum(), 1.0)
        acc = (logits.argmax(-1) == labels).mean(axis=(1, 2))
        acc = (acc * mask).sum() / jnp.maximum(mask.sum(), 1.0)
        return ce, {"accuracy": acc}

    return loss
