"""Model zoo for the example workloads (flax).

Covers the reference's example model families (SURVEY §2.6) rebuilt
TPU-first, plus a transformer LM (the long-context extension the TPU design
makes natural):

- :mod:`~tensorflowonspark_tpu.models.mnist`       — MNIST CNN
  (reference ``examples/mnist/keras/mnist_spark.py:14-20``)
- :mod:`~tensorflowonspark_tpu.models.resnet`      — ResNet56/CIFAR and
  ResNet50-v1.5/ImageNet (reference ``examples/resnet/resnet_model.py``,
  ``resnet_cifar_model.py``)
- :mod:`~tensorflowonspark_tpu.models.unet`        — U-Net segmentation
  (reference ``examples/segmentation/segmentation_spark.py:70-122``)
- :mod:`~tensorflowonspark_tpu.models.transformer` — decoder-only LM with
  full/ring/ulysses attention (sequence parallelism over the mesh)

The registry maps exported model names (checkpoint descriptors,
``checkpoint.export_model``) back to constructors so pipeline-transform
executors can rebuild a model from its name + config alone — the role
SavedModel's self-description played for the reference
(``pipeline.py:474-481``).
"""

_REGISTRY = {}


def register_model(name):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


def get_model(name, **config):
    """Instantiate a registered model by name (used by pipeline transform)."""
    if name not in _REGISTRY:
        raise KeyError("unknown model {!r}; registered: {}".format(
            name, sorted(_REGISTRY)))
    return _REGISTRY[name](**config)


# Import for registration side effects.
from tensorflowonspark_tpu.models import (  # noqa: E402,F401
    linear, mnist, resnet, transformer, twotower, unet)
