"""MNIST CNN (reference ``examples/mnist/keras/mnist_spark.py:14-20``).

The reference's example CNN family (see class docstring for the exact
topology mapping), kept deliberately small and MXU-friendly: convs in NHWC,
bf16-capable, static shapes.
"""

import flax.linen as nn
import jax.numpy as jnp

from tensorflowonspark_tpu.models import register_model


class MnistCNN(nn.Module):
    """Conv(32)->pool->Conv(64)->pool->Dense(128)->Dense(10), the reference's
    example CNN family (``mnist_spark.py:14-20`` uses Conv/MaxPool/Flatten/
    Dense(10); the estimator variant adds the second conv block,
    ``examples/mnist/estimator/mnist_spark.py:31-43``)."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        # x: [batch, 28, 28, 1] floats in [0, 1]
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


@register_model("mnist_cnn")
def build_mnist(num_classes=10, dtype="float32"):
    return MnistCNN(num_classes=num_classes, dtype=jnp.dtype(dtype))


def loss_fn(model):
    """Masked softmax cross-entropy loss for the Trainer contract."""
    import optax

    def loss(params, batch, mask):
        logits = model.apply({"params": params}, batch["image"])
        labels = batch["label"].astype(jnp.int32)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        ce = (ce * mask).sum() / jnp.maximum(mask.sum(), 1.0)
        acc = (((logits.argmax(-1) == labels) * mask).sum()
               / jnp.maximum(mask.sum(), 1.0))
        return ce, {"accuracy": acc, "logits": logits}

    return loss
