"""Two-tower retrieval model — the zoo's multi-input / multi-output family.

The reference's serving layer is defined over SavedModels with N input and M
output tensors (reference ``pipeline.py:469-518``, ``TFModel.scala:51-239``);
this model exercises that surface natively: two named inputs (``user``,
``item``) and two named outputs (``score``, ``user_embedding``), the classic
recommender two-tower shape.  Multi-input models are called by tensor-name
keyword and return a dict of named outputs — the conventions
:mod:`~tensorflowonspark_tpu.serving` serves.
"""

import flax.linen as nn
import jax.numpy as jnp

from tensorflowonspark_tpu.models import register_model


class TwoTower(nn.Module):
    """Dense towers over each input; dot-product score.

    MXU-friendly: both towers are plain matmuls, bf16-capable, static shapes.
    """

    embed_dim: int = 8
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, user, item):
        u = nn.Dense(self.embed_dim, dtype=self.dtype, name="user_tower")(
            user.astype(self.dtype))
        v = nn.Dense(self.embed_dim, dtype=self.dtype, name="item_tower")(
            item.astype(self.dtype))
        score = (u * v).sum(axis=-1).astype(jnp.float32)
        return {"score": score, "user_embedding": u.astype(jnp.float32)}


@register_model("two_tower")
def build_two_tower(embed_dim=8, dtype="float32"):
    return TwoTower(embed_dim=embed_dim, dtype=jnp.dtype(dtype))


def loss_fn(model):
    """Masked MSE on the score head, for the Trainer contract.  The batch
    carries ``user`` / ``item`` inputs and a ``label`` target score."""

    def loss(params, batch, mask):
        out = model.apply({"params": params},
                          user=batch["user"], item=batch["item"])
        err = (out["score"] - batch["label"]) ** 2
        mse = (err * mask).sum() / jnp.maximum(mask.sum(), 1.0)
        return mse, {}

    return loss
