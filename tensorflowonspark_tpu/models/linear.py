"""Linear regression model — the pipeline test workload.

The reference validates its Estimator/Model pipeline end-to-end on a
synthetic linear regression with known weights (reference
``test/test_pipeline.py:17-25,88-171``); this zoo entry plays the same role
for the framework-native pipeline, and doubles as the smallest possible
registry example.
"""

import flax.linen as nn
import jax.numpy as jnp

from tensorflowonspark_tpu.models import register_model


class Linear(nn.Module):
    features: int = 1

    @nn.compact
    def __call__(self, x):
        return nn.Dense(self.features, name="dense")(x)


@register_model("linear")
def build_linear(features=1, in_features=None):
    del in_features  # shape comes from the data; kept for descriptor clarity
    return Linear(features=features)


def loss_fn(model):
    """Masked mean-squared-error for the Trainer contract."""

    def loss(params, batch, mask):
        preds = model.apply({"params": params}, batch["x"])[:, 0]
        err = (preds - batch["y"]) ** 2
        mse = (err * mask).sum() / jnp.maximum(mask.sum(), 1.0)
        return mse, {}

    return loss
