"""ML pipeline API: Estimator/Model wrappers over the cluster lifecycle.

The reference integrates with Spark ML (reference ``pipeline.py``): a
``TFEstimator`` whose ``fit(df)`` spawns a TFoS cluster, feeds the
DataFrame, and returns a ``TFModel`` that runs cached single-node SavedModel
inference per executor (reference ``pipeline.py:330-446,454-520``).  This
module rebuilds that surface framework-natively:

- :class:`TFEstimator` / :class:`TFModel` work against any backend
  (built-in LocalBackend, or Spark when pyspark is installed — datasets may
  be plain row lists or DataFrames, see :func:`_dataset_rows`);
- the model artifact is the framework export (orbax params + model
  descriptor, :func:`~tensorflowonspark_tpu.checkpoint.export_model`)
  instead of a SavedModel — transform executors rebuild the model from the
  registry by name and run **batched jit inference** with a process-global
  cache (the role of the reference's global ``pred_fn`` cache,
  ``pipeline.py:449-451,474-481``);
- the ~18 ``Has*`` Param mixins (reference ``pipeline.py:44-272``) become
  one declarative param table with the same merge-with-argparse semantics
  (:meth:`TFParams.merge_args_params`, reference ``pipeline.py:318-327``).
"""

import argparse
import logging

import numpy as np

logger = logging.getLogger(__name__)

# pyspark.ml compatibility (reference ``pipeline.py:330-446`` subclasses
# ``pyspark.ml.Estimator/Model`` so TFoS stages compose into ML Pipelines):
# when pyspark is importable, TFEstimator/TFModel are real pipeline stages
# (ABCMeta + Params machinery + _fit/_transform dispatch); otherwise they
# degrade to plain framework classes with the same user-facing API.
try:
    from pyspark.ml import Estimator as _MLEstimator
    from pyspark.ml import Model as _MLModel

    HAS_PYSPARK_ML = True
except Exception:  # pyspark absent: framework-only classes
    _MLEstimator = object
    _MLModel = object
    HAS_PYSPARK_ML = False

# Process-global model cache for transform executors (reference
# ``pipeline.py:449-451``): survives across partitions on the same executor.
_model_cache = {}


class Namespace(object):
    """Dict/Namespace adapter (reference ``pipeline.py:275-315``): wraps a
    dict, an ``argparse.Namespace``, or another Namespace into attribute
    access with ``argv`` round-tripping."""

    def __init__(self, d=None, **kwargs):
        if d is None:
            d = {}
        elif isinstance(d, (Namespace, argparse.Namespace)):
            d = dict(vars(d))
        elif not isinstance(d, dict):
            raise ValueError("unsupported Namespace source: {!r}".format(type(d)))
        self.__dict__.update(d)
        self.__dict__.update(kwargs)

    def __iter__(self):
        return iter(self.__dict__)

    def __contains__(self, key):
        return key in self.__dict__

    def __repr__(self):
        return "Namespace({})".format(self.__dict__)

    def __eq__(self, other):
        return isinstance(other, (Namespace, argparse.Namespace)) and \
            vars(self) == vars(other)


# Declarative param table — the reference's Has* mixin surface
# (reference ``pipeline.py:44-272``) in one place: name -> (default, doc).
PARAMS = {
    "batch_size": (128, "number of records per batch"),
    "cluster_size": (1, "number of nodes in the cluster"),
    "epochs": (1, "number of epochs of training data"),
    "input_mapping": (None, "mapping of input column to tensor name"),
    "output_mapping": (None, "mapping of output tensor to output column"),
    "input_mode": (None, "input data mode (InputMode.SPARK when None)"),
    "master_node": ("chief", "job name of the chief/master node"),
    "model_dir": (None, "path to save/load model checkpoints"),
    "export_dir": (None, "path to export the trained model"),
    "model_name": (None, "registered model-zoo name for transform executors"),
    "model_config": (None, "model constructor config dict"),
    "num_ps": (0, "number of ps-like (long-running non-worker) nodes"),
    "grace_secs": (30, "grace period after feeding ends (chief export time)"),
    "steps": (1000, "max number of steps to train"),
    "steps_per_call": (1, "train steps per device dispatch (lax.scan "
                          "groups; amortizes dispatch latency)"),
    "accum_steps": (1, "gradient-accumulation microbatches per step"),
    "chunk_size": (1024, "rows per columnar feed chunk"),
    "tensorboard": (False, "launch tensorboard on the chief"),
    "feed_timeout": (600, "timeout (secs) for feeding a partition"),
}


class TFParams(object):
    """Param storage with getters/setters and argparse merging (the role of
    the reference's ``TFParams`` + ``Has*`` mixins)."""

    def __init__(self, **kwargs):
        self._tfos_params = {name: default for name, (default, _) in PARAMS.items()}
        for key, val in kwargs.items():
            self.set(key, val)
        # Cooperative init: when a subclass also derives from
        # pyspark.ml.Estimator/Model, this initializes the Params/uid
        # machinery those base classes need.
        super(TFParams, self).__init__()

    def set(self, name, value):
        if name not in PARAMS:
            raise KeyError("unknown param {!r}; known: {}".format(
                name, sorted(PARAMS)))
        self._tfos_params[name] = value
        return self

    def get(self, name):
        return self._tfos_params[name]

    def __getattr__(self, name):
        # setBatchSize/getBatchSize-style accessors for reference familiarity
        if name.startswith(("set", "get")) and len(name) > 3:
            snake = "".join(
                "_" + c.lower() if c.isupper() else c for c in name[3:]).lstrip("_")
            if snake in PARAMS:
                if name.startswith("set"):
                    return lambda value: self.set(snake, value)
                return lambda: self.get(snake)
        raise AttributeError(name)

    def merge_args_params(self, args):
        """Merge this object's params over an args Namespace: params set here
        win, args fill the rest (reference ``pipeline.py:318-327``)."""
        merged = Namespace(args)
        for name, value in self._tfos_params.items():
            setattr(merged, name, value)
        return merged


# ---------------------------------------------------------------------------
# dataset adapters
# ---------------------------------------------------------------------------

def _dataset_rows(dataset, input_columns=None):
    """Normalize a dataset to (rows, columns): rows are tuples ordered by
    sorted column name (the reference's sorted-column contract,
    ``pipeline.py:387,428-429``).

    Accepts a Spark DataFrame (``.select(...).rdd`` path), a list of dicts,
    or a list of tuples (used as-is, assumed pre-ordered).
    """
    if hasattr(dataset, "select") and hasattr(dataset, "rdd"):  # Spark DF
        cols = sorted(input_columns or dataset.columns)
        return dataset.select(cols).rdd, cols
    rows = list(dataset)
    if rows and isinstance(rows[0], dict):
        cols = sorted(input_columns or rows[0].keys())
        return [tuple(row[c] for c in cols) for row in rows], cols
    return rows, sorted(input_columns) if input_columns else None


# ---------------------------------------------------------------------------
# Estimator
# ---------------------------------------------------------------------------

class TFEstimator(TFParams, _MLEstimator):
    """Trains a model on a dataset via a framework cluster and returns a
    :class:`TFModel` (reference ``TFEstimator``, ``pipeline.py:330-391``).

    When pyspark is installed this is a real ``pyspark.ml.Estimator``, so it
    composes into ``pyspark.ml.Pipeline`` alongside other stages (reference
    ``pipeline.py:330``); without pyspark the same API works standalone.

    Args:
      train_fn: user ``main_fun(args, ctx)`` run on every node; reads its
        data with a :class:`~tensorflowonspark_tpu.datafeed.DataFeed` (the
        pipeline always uses SPARK input mode, reference ``pipeline.py:384``).
      tf_args: argparse Namespace / dict of extra args for ``train_fn``.
      backend: a :mod:`~tensorflowonspark_tpu.backend` backend or live
        SparkContext; owns the executors used for the training cluster.
    """

    def __init__(self, train_fn, tf_args, backend, **params):
        super(TFEstimator, self).__init__(**params)
        self.train_fn = train_fn
        self.args = Namespace(tf_args)
        self.backend = backend

    def fit(self, dataset, params=None):
        """Spawn a cluster, feed the dataset, return a TFModel (reference
        ``pipeline.py:367-391``)."""
        if HAS_PYSPARK_ML and params is not None:
            # defer to pyspark's fit() param-map handling -> calls _fit
            return _MLEstimator.fit(self, dataset, params)
        return self._fit(dataset)

    def _fit(self, dataset):
        from tensorflowonspark_tpu import cluster as cluster_mod

        local_args = self.merge_args_params(self.args)
        logger.info("fit: %s", vars(local_args))
        input_cols = (sorted(local_args.input_mapping)
                      if local_args.input_mapping else None)
        rows, cols = _dataset_rows(dataset, input_cols)
        if not hasattr(rows, "foreachPartition"):
            # local row list -> one partition per worker (the Spark path
            # arrives pre-partitioned as an RDD)
            from tensorflowonspark_tpu import backend as backend_mod

            num_workers = max(local_args.cluster_size - local_args.num_ps, 1)
            rows = backend_mod.partition(rows, num_workers)

        tpu_cluster = cluster_mod.run(
            self.backend, self.train_fn, local_args,
            num_executors=local_args.cluster_size,
            num_ps=local_args.num_ps,
            tensorboard=local_args.tensorboard,
            input_mode=cluster_mod.InputMode.SPARK,
            master_node=local_args.master_node,
        )
        tpu_cluster.train(rows, num_epochs=local_args.epochs,
                          feed_timeout=local_args.feed_timeout,
                          chunk_size=local_args.chunk_size)
        tpu_cluster.shutdown(grace_secs=local_args.grace_secs)
        return TFModel(local_args, backend=self.backend)


# ---------------------------------------------------------------------------
# Model
# ---------------------------------------------------------------------------

class TFModel(TFParams, _MLModel):
    """Batched, cached, per-executor model inference over a dataset
    (reference ``TFModel``, ``pipeline.py:394-446``).

    When pyspark is installed this is a real ``pyspark.ml.Model`` pipeline
    stage; ``transform(df)`` then returns a DataFrame with the prediction
    column (reference ``_transform`` builds one, ``pipeline.py:445-446``).

    Loads the framework export (``export_dir``) on each executor — model
    rebuilt from the registry via the export descriptor, params from orbax —
    and maps partitions to predictions with a process-global cache, exactly
    the reference's single-node-inference design (model must fit on one
    host's devices; reference ``pipeline.py:6-9``).
    """

    def __init__(self, args=None, backend=None, **params):
        super(TFModel, self).__init__(**params)
        if args is not None:  # inherit estimator params (reference TFModel(args))
            for name in PARAMS:
                if name in args:
                    self._tfos_params[name] = getattr(args, name)
        self.backend = backend

    def transform(self, dataset, params=None, num_partitions=None):
        """Run inference over the dataset (reference ``_transform``,
        ``pipeline.py:419-446``).  Returns a DataFrame (prediction column
        appended per the output_mapping) when given a DataFrame, else a list
        of output rows."""
        if HAS_PYSPARK_ML and params is not None:
            return _MLModel.transform(self, dataset, params)
        return self._transform(dataset, num_partitions)

    def _output_columns(self):
        """Output column names in mapping order (``["prediction"]`` when no
        output_mapping is set)."""
        out_map = self.get("output_mapping")
        return list(out_map.values()) if out_map else ["prediction"]

    def _transform(self, dataset, num_partitions=None):
        from tensorflowonspark_tpu import backend as backend_mod

        export_dir = self.get("export_dir") or self.get("model_dir")
        assert export_dir, "export_dir (or model_dir) must be set for transform"
        input_cols = (sorted(self.get("input_mapping"))
                      if self.get("input_mapping") else None)
        rows, cols = _dataset_rows(dataset, input_cols)
        run = _run_model_fn(export_dir, self.get("batch_size"),
                            input_mapping=self.get("input_mapping"),
                            output_mapping=self.get("output_mapping"))

        out_cols = self._output_columns()
        if hasattr(rows, "mapPartitions"):  # Spark RDD path
            out_rdd = rows.mapPartitions(run)
            spark = getattr(dataset, "sparkSession", None)
            if spark is None:
                return out_rdd
            # DataFrame in -> DataFrame out, one column per output tensor
            # (reference pipeline.py:445-446; M columns like TFModel.scala)
            if len(out_cols) == 1:
                out_rdd = out_rdd.map(lambda p: (p,))
            return spark.createDataFrame(out_rdd, out_cols)
        num_partitions = num_partitions or getattr(
            self.backend, "num_executors", 1)
        parts = backend_mod.partition(rows, num_partitions)
        if self.backend is None:
            return [out for part in parts for out in run(iter(part))]
        results = self.backend.map_partitions(parts, run)
        return [out for part in results if part for out in part]


def _run_model_fn(export_dir, batch_size, input_mapping=None,
                  output_mapping=None):
    """Build the per-partition inference closure (reference ``_run_model``,
    ``pipeline.py:454-520``); the closure is cloudpickled to executors.
    Rows in, output rows out — a bare value per row for single-output
    models, a tuple of output-column values for multi-output models."""

    def _run_model(iterator):
        import tensorflowonspark_tpu.pipeline as pipeline_mod

        # Process-global cache: load/compile once per executor process, reuse
        # across partitions (reference pipeline.py:474-481).  The module must
        # be referenced absolutely — this closure runs cloudpickled, so its
        # own module globals would be by-value copies.  batch_size is part
        # of the key: a later transform with a different batch size must not
        # silently reuse a server padded for the old one.
        key = (export_dir, batch_size)
        server = pipeline_mod._model_cache.get(key)
        if server is None:
            from tensorflowonspark_tpu import serving

            server = serving.ModelServer(export_dir, batch_size)
            pipeline_mod._model_cache[key] = server
        return list(server.run_rows(iterator, input_mapping=input_mapping,
                                    output_mapping=output_mapping))

    return _run_model


def yield_batch(iterator, batch_size):
    """Generate ``(rows, count)`` batches from a row iterator (reference
    ``yield_batch``, ``pipeline.py:540-562``)."""
    batch = []
    for row in iterator:
        batch.append(row)
        if len(batch) >= batch_size:
            yield batch, len(batch)
            batch = []
    if batch:
        yield batch, len(batch)
