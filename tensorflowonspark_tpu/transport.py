"""Shared zero-copy stream framing for every TCP data path in the repo.

One wire shape, three users: the data-service split streams
(``dataservice.py``), the serving gateway's request/response batches
(``gateway.py``), and whatever subsystem grows a bulk path next.  The
framing was born in the data plane and proved there (2.1 GB/s loopback
ingest, PR 5/10); this module lifts it out so serving batches ride the
exact same colv1 frames as training chunks instead of a fifth bespoke
protocol.

Frame layout: 4-byte big-endian payload length + 1-byte kind byte,
then the payload.  Four kinds:

* ``K_JSON``   — UTF-8 JSON control message (hellos, acks, aborts),
* ``K_COLV1``  — one ``wire.py`` colv1 columnar frame (zero-copy decode
  on receipt; optional per-column compression negotiated at hello),
* ``K_PICKLE`` — pickled python payload, the object/ragged fallback,
* ``K_TRACED`` — a :data:`THEADER` request-trace header (flow id +
  reserved model/version tags) wrapping a K_COLV1/K_PICKLE payload, so
  the serving request flow id rides the wire with its batch.

The module level keeps the bare socket helpers (``recv_exact`` /
``recv_frame`` / ``send_frame`` / ``send_json`` / ``addr_tuple``) so
existing call sites keep their hot-path shape; the :class:`Transport`
class wraps a connected socket with the rest of the protocol contract —
codec negotiation, send/receive counters, columnar encode with pickle
fallback, and in-band typed aborts — so new endpoints don't re-derive
those semantics by hand.
"""

import json
import pickle
import socket
import struct
import threading

from tensorflowonspark_tpu import wire

# Data-stream framing: 4-byte big-endian payload length + 1-byte kind.
DHEADER = struct.Struct(">IB")
K_JSON = 0     # UTF-8 JSON control message
K_COLV1 = 1    # one wire.py colv1 frame (zero-copy decode on receipt)
K_PICKLE = 2   # pickled payload (object/ragged fallback)
K_TRACED = 3   # THEADER trace header + an inner K_COLV1/K_PICKLE payload

# Request-plane trace header riding ahead of a columnar payload inside a
# ``K_TRACED`` frame: u64 flow id (``telemetry.Tracer.new_flow_id``), u8
# inner kind byte (K_COLV1 or K_PICKLE), then u16 model tag + u16 version
# tag.  The tags are reserved and always 0 today — serving v2's multi-model
# dimension rides in them without another frame-format bump.  Keeping the
# trace header at the transport layer (not inside the colv1 fixed header)
# means wire.py frames stay bit-identical with the data plane's, and a
# request with no live tracer skips the wrapper entirely.
THEADER = struct.Struct(">QBHH")


class TransportError(RuntimeError):
    """Protocol-level failure on a transport stream (bad hello, unknown
    frame kind, or a peer-sent abort surfaced in-band)."""


def recv_exact(sock, n):
    # Returns a bytearray, not bytes: a final bytes(buf) copy of every
    # ~800 KB chunk payload caps the consumer's aggregate ingest around
    # 750 MB/s on loopback; skipping it nearly triples the framing ceiling.
    # Callers treat the buffer as immutable (frombuffer views pin it).
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        k = sock.recv_into(view[got:], n - got)
        if k == 0:
            raise EOFError("connection closed mid-frame")
        got += k
    return buf


def recv_frame(sock):
    """One ``(kind, payload)`` data frame; raises EOFError on a closed peer."""
    length, kind = DHEADER.unpack(recv_exact(sock, DHEADER.size))
    return kind, recv_exact(sock, length)


# Below this, header+payload are sent as one concatenated buffer so small
# control frames never sit behind Nagle/delayed-ACK interactions with a
# previous partial segment; at or above it the payload copy costs more than
# the second sendall (TCP_NODELAY is set on every data socket anyway).
SEND_COPY_MAX = 64 * 1024


def send_frame(sock, kind, payload):
    header = DHEADER.pack(len(payload), kind)
    if len(payload) < SEND_COPY_MAX:
        sock.sendall(header + payload)
    else:
        sock.sendall(header)
        sock.sendall(payload)


def send_json(sock, obj):
    send_frame(sock, K_JSON, json.dumps(obj).encode("utf-8"))


def addr_tuple(addr):
    """Normalize ``(host, port)`` / ``[host, port]`` / ``"host:port"``."""
    if isinstance(addr, str):
        host, _, port = addr.rpartition(":")
        return (host, int(port))
    return (addr[0], int(addr[1]))


class Transport(object):
    """A connected stream speaking the shared framing protocol.

    Wraps an already-connected socket (either side of the connection) and
    owns the per-stream contract the data plane established:

    * **codec negotiation** — one JSON hello round; the server picks the
      first mutually supported codec via :func:`wire.negotiate_codec` and
      every later colv1 frame on the stream uses it,
    * **counters** — frames/bytes in each direction plus a
      ``compress_stats`` dict fed to ``wire.frame_bytes`` (raw vs wire
      bytes, per-codec column counts) for heartbeat export,
    * **columnar send with fallback** — ``send_columns`` tries the
      zero-copy colv1 encoding and silently falls back to pickle for
      object/ragged columns, exactly like the feed-worker stream path,
    * **abort semantics** — ``send_abort`` delivers a typed in-band
      control message (the split-abort pattern) so a peer mid-stream
      learns *why* instead of seeing a bare EOF.

    Sends are serialized by an internal lock so multiple producer threads
    can share one stream; receives are left to a single reader thread
    (both the data service and the gateway dedicate one).
    """

    def __init__(self, sock, codec=None):
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # not TCP (unix socketpair in tests): Nagle doesn't apply
        self.sock = sock
        self.codec = codec
        self.frames_sent = 0
        self.frames_received = 0
        self.bytes_sent = 0
        self.bytes_received = 0
        self.colv1_sent = 0
        self.pickle_sent = 0
        self.compress_stats = {}
        self._send_lock = threading.Lock()

    # -- handshake ----------------------------------------------------------

    def client_hello(self, extra=None):
        """Send the client side of the codec handshake and adopt the codec
        the server picks.  Returns the server's hello-reply dict."""
        hello = {"type": "hello", "codecs": wire.supported_codecs()}
        if extra:
            hello.update(extra)
        self.send_control(hello)
        msg = self.recv_control()
        self.codec = msg.get("codec") or None
        return msg

    def server_hello(self, hello, extra=None):
        """Answer a client hello: negotiate the codec and confirm it."""
        self.codec = wire.negotiate_codec(hello.get("codecs"))
        reply = {"type": "hello_ok", "codec": self.codec}
        if extra:
            reply.update(extra)
        self.send_control(reply)
        return self.codec

    # -- send path ----------------------------------------------------------

    def send_control(self, obj):
        payload = json.dumps(obj).encode("utf-8")
        self._send(K_JSON, payload)

    def send_abort(self, code, message, **fields):
        """Typed in-band abort (the data plane's split_abort pattern): the
        peer's reader surfaces it instead of a bare connection reset."""
        msg = {"type": "abort", "code": code, "message": message}
        msg.update(fields)
        self.send_control(msg)

    def send_columns(self, columns, count, tuple_rows=False, flow_id=None):
        """Send one batch of columns: colv1 when framable, pickle fallback.

        A truthy ``flow_id`` wraps the payload in a ``K_TRACED`` frame so
        the request's trace flow id travels with its data (one small-header
        copy on the traced path only).  Returns the *inner* kind byte so
        callers count formats the same with or without tracing.
        """
        kind = K_PICKLE
        payload = None
        try:
            payload = wire.frame_bytes(
                columns, count, tuple_rows,
                codec=self.codec, stats=self.compress_stats)
            if payload is not None:
                kind = K_COLV1
        except wire.FrameError:
            payload = None
        if payload is None:
            payload = pickle.dumps((columns, count, tuple_rows),
                                   protocol=pickle.HIGHEST_PROTOCOL)
        if flow_id:
            payload = THEADER.pack(int(flow_id), kind, 0, 0) + bytes(payload)
            self._send(K_TRACED, payload)
        else:
            self._send(kind, payload)
        if kind == K_COLV1:
            self.colv1_sent += 1
        else:
            self.pickle_sent += 1
        return kind

    def _send(self, kind, payload):
        with self._send_lock:
            send_frame(self.sock, kind, payload)
            self.frames_sent += 1
            self.bytes_sent += DHEADER.size + len(payload)

    # -- receive path -------------------------------------------------------

    def recv_message(self):
        """One frame as ``(kind, decoded)``.

        ``K_JSON`` frames come back as dicts — except ``type: "abort"``
        which raises :class:`TransportError` so mid-stream failures can't
        be mistaken for data.  ``K_COLV1`` / ``K_PICKLE`` payloads are
        returned raw for the caller to decode (column decode wants
        caller-controlled ``copy`` semantics).
        """
        kind, payload = recv_frame(self.sock)
        self.frames_received += 1
        self.bytes_received += DHEADER.size + len(payload)
        if kind == K_JSON:
            msg = json.loads(bytes(payload).decode("utf-8"))
            if isinstance(msg, dict) and msg.get("type") == "abort":
                raise TransportError("peer abort [{}]: {}".format(
                    msg.get("code"), msg.get("message")))
            return kind, msg
        return kind, payload

    def recv_control(self):
        kind, msg = self.recv_message()
        if kind != K_JSON:
            raise TransportError(
                "expected control frame, got kind={}".format(kind))
        return msg

    @staticmethod
    def split_traced(payload):
        """Split a ``K_TRACED`` payload into ``(flow_id, inner_kind,
        inner_payload)``.  The inner payload is a zero-copy memoryview into
        ``payload``; the reserved model/version tags are discarded."""
        if len(payload) < THEADER.size:
            raise TransportError("traced frame shorter than THEADER")
        flow_id, inner_kind, _model, _version = THEADER.unpack_from(
            memoryview(payload), 0)
        if inner_kind not in (K_COLV1, K_PICKLE):
            raise TransportError(
                "traced frame wraps kind={}".format(inner_kind))
        return flow_id, inner_kind, memoryview(payload)[THEADER.size:]

    @staticmethod
    def decode_columns(kind, payload, copy=False):
        """Decode a ``send_columns`` payload back to
        ``(columns, count, tuple_rows)``.  ``copy=False`` keeps colv1
        columns as views pinning the receive buffer (zero-copy).  A
        ``K_TRACED`` frame decodes transparently (flow id discarded —
        callers who want it use :meth:`split_traced` first)."""
        if kind == K_TRACED:
            _, kind, payload = Transport.split_traced(payload)
        if kind == K_COLV1:
            return wire.decode(payload, copy=copy)
        if kind == K_PICKLE:
            return pickle.loads(bytes(payload))
        raise TransportError("not a columnar frame: kind={}".format(kind))

    # -- lifecycle ----------------------------------------------------------

    def counters(self):
        out = {"frames_sent": self.frames_sent,
               "frames_received": self.frames_received,
               "bytes_sent": self.bytes_sent,
               "bytes_received": self.bytes_received,
               "colv1_sent": self.colv1_sent,
               "pickle_sent": self.pickle_sent}
        out.update(self.compress_stats)
        return out

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass
