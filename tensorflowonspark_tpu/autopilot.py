"""Autopilot: closed-loop performance controller over live telemetry.

The observability stack measures everything — goodput breakdown, infeed
starvation, data-service queue fill, cache evictions, serving batch fill
and p99 — but until now nothing *acted* on it (ROADMAP item 4).  This
module closes the loop with a driver-side controller thread that ticks
over the observatory :class:`~tensorflowonspark_tpu.observatory.SampleRing`
(the watchtower pattern) and runs gradient-free hill-climbing over live
performance knobs:

===========================  =======================  =====================
knob                         plane                    steered by
===========================  =======================  =====================
``infeed_prefetch``          ShardedFeed (node)       infeed-starved wall
                                                      fraction
``dataservice_queue_bound``  ServiceFeed (node)       ``dataservice_queue_
                                                      sat_pct_max``
``dataservice_cache_budget`` FeedWorker chunk cache   cache-thrash eviction
                                                      evidence
``wire_codec``               stream hello (node)      measured compress
                                                      ratio vs CPU cost
``serving_max_wait_ms``      GatewayServer            p99 vs batch fill
``serving_max_batch``        GatewayServer            p99 vs batch fill
===========================  =======================  =====================

Guardrails, in the order they gate an action:

- **hysteresis** — a sensor must fire on ``confirm_ticks`` consecutive
  control ticks before a proposal is minted (one noisy window never
  turns a knob), and a post-actuation objective move inside
  ``hysteresis_frac`` counts as neutral, never as improvement;
- **per-knob cooldown** — after an action settles (kept OR reverted) the
  knob is frozen for ``cooldown_secs`` (``revert_cooldown_secs`` after a
  revert), so the controller cannot flap;
- **revert-on-regression** — every applied action records the steered
  objective before actuation, waits ``settle_ticks``, re-measures, and
  rolls the knob back within that one control window when the objective
  regressed beyond ``revert_margin_frac`` (the journal records
  ``reverted`` with the measured before/after);
- **one action in flight** — a new proposal is never considered while an
  applied action is still settling, so effects are attributable.

Every action is journaled (``proposed`` → ``applied`` → ``effect`` →
``kept``/``reverted``) to a flush-per-write JSONL next to the watchtower
journal, with a **dry-run mode** that proposes and journals but never
actuates.  Actuation itself rides the existing heartbeat-reply channel:
the controller pushes ``{knob: value}`` into
:class:`~tensorflowonspark_tpu.reservation.KnobCoordinator` and each
node's next beat reply carries the ``knobs`` dict exactly once (the
``PROF``/``reregister`` pattern).  See docs/AUTOPILOT.md.
"""

import logging
import math
import threading
import time

from . import telemetry
from .guardrails import STAGES, Guardrails, JsonlJournal
from .watchtower import read_journal as _read_journal, window_deltas

logger = logging.getLogger(__name__)

JOURNAL_VERSION = 1

# STAGES (the proposed→applied→effect→kept/reverted lifecycle vocabulary)
# now lives in guardrails.py, shared with the remediator; re-exported here
# for compatibility.
assert STAGES[0] == "proposed"

#: every tunable threshold in one place; ``cluster.run(..., autopilot={...})``
#: overrides key-wise (unknown keys raise, same contract as the watchtower)
DEFAULT_CONFIG = {
    # control tick cadence and the sliding measurement window
    "interval_secs": 1.0,
    "window_secs": 15.0,
    # hysteresis: consecutive firing ticks before a proposal is minted
    "confirm_ticks": 2,
    # ticks between actuation and judging its effect (the control window)
    "settle_ticks": 3,
    # per-knob freeze after an action settles; longer after a revert so a
    # knob that just hurt the run is not retried while conditions match
    "cooldown_secs": 10.0,
    "revert_cooldown_secs": 60.0,
    # objective moves inside this relative band are neutral (kept, but
    # never counted as improvement); beyond revert_margin_frac the action
    # is rolled back
    "hysteresis_frac": 0.10,
    "revert_margin_frac": 0.25,
    # propose + journal but never actuate
    "dry_run": False,
    # sensor thresholds (vocabulary shared with the watchtower rules)
    "infeed_starved_frac": 0.3,
    "min_events": 5,
    "queue_sat_pct": 90.0,
    "cache_thrash_min_evictions": 8,
    "cache_thrash_evict_hit_ratio": 1.0,
    # a negotiated codec whose measured ratio is below this is not paying
    # for its CPU cost
    "codec_min_ratio": 1.1,
    # serving objective: 0 disarms the SLO comparison (fill-only steering)
    "latency_slo_p99_us": 0.0,
    "batch_fill_lo_pct": 50.0,
    "batch_fill_hi_pct": 90.0,
    # megastep K steering (train_steps_per_call): raise K when the
    # between-dispatch host gap per DISPATCHED step is above the
    # threshold (host overhead a longer scan would amortize); back K off
    # when the feed cannot keep groups full (starved wall fraction at or
    # above the threshold while K > 1)
    "steps_per_call_gap_hi_us": 1500.0,
    "steps_per_call_starved_frac": 0.5,
    # bounded in-memory action log + journal snapshot cadence
    "max_actions": 64,
    "journal_snapshot_secs": 10.0,
    # per-knob overrides of DEFAULT_KNOBS ({"infeed_prefetch": {...}})
    "knobs": {},
}

#: per-knob bounds and driver-side shadow of the current value.  ``initial``
#: None means "unknown" — a numeric knob cannot be stepped from an unknown
#: value, so the cluster wiring (or test) must supply it; categorical knobs
#: (``choices``) actuate absolute values and need no initial.
DEFAULT_KNOBS = {
    "infeed_prefetch": {"initial": None, "min": 1, "max": 16,
                        "integer": True, "target": "node"},
    "train_steps_per_call": {"initial": None, "min": 1, "max": 64,
                             "integer": True, "target": "node"},
    "dataservice_queue_bound": {"initial": 2, "min": 2, "max": 64,
                                "integer": True, "target": "node"},
    "dataservice_cache_budget": {"initial": None, "min": 8 << 20,
                                 "max": 2 << 30, "integer": True,
                                 "target": "worker"},
    "wire_codec": {"initial": None, "choices": ["auto", "off"],
                   "target": "node"},
    "serving_max_wait_ms": {"initial": None, "min": 0.5, "max": 50.0,
                            "integer": False, "target": "gateway"},
    "serving_max_batch": {"initial": None, "min": 1, "max": 1024,
                          "integer": True, "target": "gateway"},
}

#: watchtower rule -> (knob, direction): an admitted alert becomes a
#: standing proposal hint, so the watchtower's own thresholds can arm a
#: knob even when the autopilot's (looser or tighter) sensor has not fired
ALERT_HINTS = {
    "infeed_starved": ("infeed_prefetch", +1),
    "dataservice_saturation": ("dataservice_queue_bound", +1),
    "cache_thrash": ("dataservice_cache_budget", +1),
    # slo_budget_burn superseded latency_slo_burn (PR 19); the old name
    # stays mapped so journal replays of earlier runs still resolve hints
    "latency_slo_burn": ("serving_max_wait_ms", -1),
    "slo_budget_burn": ("serving_max_wait_ms", -1),
}

_EPS = 1e-9


def _is_num(v):
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def merge_config(config):
    """Key-wise merge over :data:`DEFAULT_CONFIG`; unknown keys raise so a
    typo'd threshold fails loudly instead of silently not steering."""
    cfg = dict(DEFAULT_CONFIG)
    cfg["knobs"] = {}
    for k, v in (config or {}).items():
        if k not in DEFAULT_CONFIG:
            raise ValueError("unknown autopilot config key: %r (known: %s)"
                             % (k, ", ".join(sorted(DEFAULT_CONFIG))))
        cfg[k] = v
    knobs = {}
    for name, spec in DEFAULT_KNOBS.items():
        knobs[name] = dict(spec)
    for name, over in (config or {}).get("knobs", {}).items():
        if name not in DEFAULT_KNOBS:
            raise ValueError("unknown autopilot knob: %r (known: %s)"
                             % (name, ", ".join(sorted(DEFAULT_KNOBS))))
        knobs[name].update(over or {})
    cfg["knobs"] = knobs
    return cfg


class Autopilot(object):
    """Driver-side closed-loop controller over the observatory ring.

    Args:
      ring: the :class:`~tensorflowonspark_tpu.observatory.SampleRing` the
        reservation server feeds (``server.sample_ring``) — anything with
        a ``series()`` method works (replay uses a static stand-in).
      actuator: ``fn({knob: value})`` that delivers knob updates to the
        cluster — in production ``KnobCoordinator.push``, fanned out on
        heartbeat replies.  ``None`` (or ``dry_run``) journals proposals
        without actuating.
      snapshot_fn: zero-arg callable returning the ``{"nodes", ...}``
        metrics snapshot, journaled periodically so replay has the series.
      config: key-wise overrides of :data:`DEFAULT_CONFIG`.
      journal_path: append-only flush-per-write JSONL; ``None`` disables.
      on_action: optional ``fn(record)`` per journaled action stage.
      clock: injectable time source (tests, replay).
    """

    def __init__(self, ring, actuator=None, snapshot_fn=None, config=None,
                 journal_path=None, on_action=None, clock=time.time,
                 resume_values=None):
        """``resume_values``: optional ``{knob: value}`` overriding each
        knob's configured ``initial`` — a coordinator recovered from its
        journal hands the fleet's standing knob state here
        (``KnobCoordinator.current()``), so a controller restarted after a
        failover resumes from where the fleet actually IS instead of
        re-walking every retune from the configured defaults."""
        self.config = merge_config(config)
        self.ring = ring
        self.actuator = actuator
        self._snapshot_fn = snapshot_fn
        self._on_action = on_action
        self._clock = clock
        self.journal_path = journal_path
        self._journal = JsonlJournal(journal_path, owner="autopilot")
        self._last_journal_snap = 0.0
        self.dry_run = bool(self.config["dry_run"])
        # driver-side shadow of each knob's current value
        self._values = {name: spec.get("initial")
                        for name, spec in self.config["knobs"].items()}
        for name, value in (resume_values or {}).items():
            if name in self._values and value is not None:
                self._values[name] = value
        # shared gating state: streaks + cooldowns + the one in-flight slot
        self._guard = Guardrails(self.config["cooldown_secs"],
                                 self.config["revert_cooldown_secs"])
        self._hints = {}           # knob -> (direction, alert_time, rule)
        self._seq = 0
        self._ticks = 0
        self._actions = []         # bounded recent action records
        self._counts = {}          # stage -> count
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        """Start the control thread (idempotent); returns self."""
        if self._thread is not None:
            return self
        self._journal_meta()
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="tfos-autopilot", daemon=True)
        self._thread.start()
        telemetry.get_tracer().instant(
            "autopilot/start", dry_run=self.dry_run,
            knobs=len(self._values))
        return self

    def stop(self):
        """Stop the thread, journal a final snapshot, close the journal.
        Idempotent."""
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
            self._journal_snapshot(force=True)
        self._journal.close()

    def _loop(self):
        interval = self.config["interval_secs"]
        while not self._stop.wait(interval):
            try:
                self.tick()
            except Exception:  # the controller must never take the run down
                logger.warning("autopilot tick failed", exc_info=True)

    # -- watchtower bridge -------------------------------------------------

    def observe_alert(self, alert):
        """Watchtower ``on_alert`` hook: an admitted alert becomes a
        standing proposal hint for the mapped knob (the watchtower's
        threshold arms the sensor even when the autopilot's own has not
        fired).  Unmapped rules are ignored."""
        hint = ALERT_HINTS.get((alert or {}).get("rule"))
        if hint is None:
            return
        knob, direction = hint
        with self._lock:
            self._hints[knob] = (direction, alert.get("time", self._clock()),
                                 alert.get("rule"))

    # -- control tick ------------------------------------------------------

    def tick(self, now=None):
        """One control pass; returns the action records journaled this
        tick.  Public so tests and replay drive it directly."""
        now = self._clock() if now is None else now
        with self._lock:
            self._ticks += 1
            tick = self._ticks
        emitted = []
        win = self._measure(now)
        # settle phase first: while an action is in flight nothing else
        # moves, so its effect stays attributable
        if self._guard.pending is not None:
            emitted.extend(self._judge_pending(win, now, tick))
        elif win["nodes"]:
            emitted.extend(self._consider(win, now, tick))
        self._journal_snapshot(now=now)
        return emitted

    # -- measurement -------------------------------------------------------

    def _measure(self, now):
        """Aggregate the in-window telemetry: summed counter deltas across
        nodes, per-node starved fractions, and recent gauge maxima."""
        window = self.config["window_secs"]
        deltas = {}
        gauges = {}
        per_node = {}
        span = 0.0
        nodes = 0
        for node, samples in self.ring.series().items():
            recent = [(ts, c) for ts, c in samples if ts >= now - window]
            wd = window_deltas(recent)
            if wd is not None:
                nodes += 1
                span = max(span, wd["span_secs"])
                per_node[node] = wd
                for k, v in wd["deltas"].items():
                    deltas[k] = deltas.get(k, 0) + v
            # gauges (_hwm/_max) are per-beat latched values the delta walk
            # skips: take the max over the window's recent samples
            for _ts, counters in recent[-5:]:
                for k, v in counters.items():
                    if k.endswith(("_hwm", "_max")) and _is_num(v) \
                            and math.isfinite(v):
                        gauges[k] = max(gauges.get(k, 0), v)
        return {"deltas": deltas, "gauges": gauges, "per_node": per_node,
                "span_secs": span, "nodes": nodes}

    def _starved_frac(self, win):
        """Worst per-node infeed-starved wall fraction (the starving node
        is the signal; averaging across healthy peers would hide it)."""
        worst = None
        for wd in win["per_node"].values():
            d = wd["deltas"]
            if d.get("dispatch_count", 0) < self.config["min_events"]:
                continue
            span = wd["span_secs"]
            if span <= 0:
                continue
            frac = d.get("goodput_infeed_starved_us", 0) / (span * 1e6)
            if frac >= 0 and (worst is None or frac > worst):
                worst = frac
        return worst

    def _gap_per_step(self, win):
        """Worst per-node between-dispatch host gap per DISPATCHED train
        step (µs) — the host overhead a longer megastep scan amortizes.
        Per-step (not per-dispatch): otherwise raising K would look worse
        as each bigger group legitimately waits longer for its data."""
        worst = None
        for wd in win["per_node"].values():
            d = wd["deltas"]
            steps = d.get("train_steps_total", 0)
            if steps < self.config["min_events"]:
                continue
            gap = d.get("dispatch_gap_us", 0) / steps
            if worst is None or gap > worst:
                worst = gap
        return worst

    # objectives are "lower is better" so kept/reverted logic is uniform
    def _objective(self, knob, win):
        d, g, span = win["deltas"], win["gauges"], max(win["span_secs"],
                                                      _EPS)
        if knob == "infeed_prefetch":
            return self._starved_frac(win)
        if knob == "train_steps_per_call":
            return self._gap_per_step(win)
        if knob == "dataservice_queue_bound":
            return g.get("dataservice_queue_sat_pct_max")
        if knob == "dataservice_cache_budget":
            if "dataservice_cache_evictions" not in d:
                return None
            return d.get("dataservice_cache_evictions", 0) / span
        if knob == "wire_codec":
            if "dataservice_items" not in d:
                return None
            return -(d.get("dataservice_items", 0) / span)
        if knob in ("serving_max_wait_ms", "serving_max_batch"):
            return g.get("serving_p99_us_max")
        return None

    # -- sensors -----------------------------------------------------------

    def _sense(self, knob, win):
        """Return ``{"direction", "signal", "value"}`` when the knob's
        steering signal fires this tick, else ``None``."""
        cfg = self.config
        d, g = win["deltas"], win["gauges"]
        if knob == "infeed_prefetch":
            frac = self._starved_frac(win)
            if frac is not None and frac >= cfg["infeed_starved_frac"]:
                return {"direction": +1, "signal": "infeed_starved",
                        "value": round(frac, 4)}
        elif knob == "train_steps_per_call":
            gap = self._gap_per_step(win)
            if gap is not None and gap >= cfg["steps_per_call_gap_hi_us"]:
                return {"direction": +1, "signal": "dispatch_gap_per_step",
                        "value": round(gap, 1)}
            frac = self._starved_frac(win)
            cur = self._values.get("train_steps_per_call")
            if frac is not None and cur is not None and cur > 1 and \
                    frac >= cfg["steps_per_call_starved_frac"]:
                # groups are waiting on the feed: a smaller K restores
                # overlap instead of parking the device K batches at a time
                return {"direction": -1, "signal": "group_starved",
                        "value": round(frac, 4)}
        elif knob == "dataservice_queue_bound":
            sat = g.get("dataservice_queue_sat_pct_max")
            if sat is not None and sat >= cfg["queue_sat_pct"]:
                return {"direction": +1, "signal": "dataservice_saturation",
                        "value": sat}
        elif knob == "dataservice_cache_budget":
            ev = d.get("dataservice_cache_evictions", 0)
            hits = d.get("dataservice_cache_hit", 0)
            if ev >= cfg["cache_thrash_min_evictions"] and \
                    ev >= cfg["cache_thrash_evict_hit_ratio"] * max(hits, 1):
                return {"direction": +1, "signal": "cache_thrash",
                        "value": ev}
        elif knob == "wire_codec":
            ratio = g.get("wire_compress_ratio_max")
            if ratio and 0 < ratio < cfg["codec_min_ratio"] and \
                    self._values.get("wire_codec") != "off":
                return {"direction": 0, "signal": "codec_not_paying",
                        "value": ratio, "to": "off"}
        elif knob == "serving_max_wait_ms":
            fill = g.get("serving_batch_fill_pct_max")
            p99 = g.get("serving_p99_us_max")
            slo = cfg["latency_slo_p99_us"]
            if d.get("serving_requests", 0) > 0 and fill is not None \
                    and fill < cfg["batch_fill_lo_pct"] \
                    and (not slo or (p99 or 0) > slo):
                # waiting is not filling batches: it only buys latency
                return {"direction": -1, "signal": "p99_vs_batch_fill",
                        "value": fill}
        elif knob == "serving_max_batch":
            fill = g.get("serving_batch_fill_pct_max")
            p99 = g.get("serving_p99_us_max")
            slo = cfg["latency_slo_p99_us"]
            if d.get("serving_requests", 0) > 0 and fill is not None \
                    and fill >= cfg["batch_fill_hi_pct"] \
                    and (not slo or (p99 or 0) < 0.7 * slo):
                # batches leave full with latency headroom: admit more
                return {"direction": +1, "signal": "p99_vs_batch_fill",
                        "value": fill}
        return None

    def _step(self, knob, direction, sensed):
        """Hill-climb step: next value for ``knob`` or ``None`` when it
        cannot move (unknown current value, pinned at a bound)."""
        spec = self.config["knobs"][knob]
        if "choices" in spec:
            to = sensed.get("to")
            return to if to in spec["choices"] else None
        cur = self._values.get(knob)
        if cur is None:
            return None  # numeric knob with no known current value
        nxt = cur * 2 if direction > 0 else cur / 2.0
        if spec.get("integer", True):
            nxt = int(max(nxt, cur + 1) if direction > 0
                      else min(nxt, cur - 1))
        nxt = min(max(nxt, spec["min"]), spec["max"])
        if spec.get("integer", True):
            nxt = int(nxt)
        return None if nxt == cur else nxt

    # -- decision ----------------------------------------------------------

    def _consider(self, win, now, tick):
        emitted = []
        window = self.config["window_secs"]
        for knob in self.config["knobs"]:
            if self._guard.in_cooldown(knob, now):
                continue
            sensed = self._sense(knob, win)
            if sensed is None:
                # a fresh watchtower alert stands in for a local sensor
                hint = self._hints.get(knob)
                if hint and now - hint[1] <= window:
                    sensed = {"direction": hint[0], "signal": hint[2],
                              "value": None, "hint": True}
            if sensed is None:
                self._guard.clear_streak(knob)
                continue
            streak = self._guard.bump_streak(knob)
            if streak < self.config["confirm_ticks"]:
                continue  # hysteresis: one noisy window never turns a knob
            to = self._step(knob, sensed["direction"], sensed)
            if to is None:
                self._guard.clear_streak(knob)
                continue
            emitted.extend(self._act(knob, to, sensed, win, now, tick))
            break  # one action in flight at a time
        return emitted

    def _act(self, knob, to, sensed, win, now, tick):
        frm = self._values.get(knob)
        objective = self._objective(knob, win)
        self._seq += 1
        base = {"seq": self._seq, "knob": knob,
                "target": self.config["knobs"][knob].get("target"),
                "from": frm, "to": to, "signal": sensed["signal"],
                "value": sensed.get("value"), "tick": tick}
        out = [self._record(dict(base, stage="proposed",
                                 objective_before=objective, time=now))]
        self._guard.clear_streak(knob)
        self._hints.pop(knob, None)
        if self.dry_run or self.actuator is None:
            # dry run: propose + journal, never actuate; cooldown still
            # applies so the journal is a decision stream, not a firehose
            self._guard.start_cooldown(knob, now)
            return out
        try:
            self.actuator({knob: to})
        except Exception:
            logger.warning("autopilot actuation failed for %s", knob,
                           exc_info=True)
            self._guard.start_cooldown(knob, now)
            return out
        self._values[knob] = to
        self._guard.begin(dict(base, objective_before=objective,
                               applied_tick=tick, applied_time=now))
        out.append(self._record(dict(base, stage="applied",
                                     objective_before=objective, time=now)))
        return out

    def _judge_pending(self, win, now, tick):
        pend = self._guard.pending
        if tick - pend["applied_tick"] < self.config["settle_ticks"]:
            return []
        knob = pend["knob"]
        before = pend["objective_before"]
        after = self._objective(knob, win)
        base = {k: pend[k] for k in ("seq", "knob", "target", "from", "to",
                                     "signal", "value")}
        out = [self._record(dict(base, stage="effect", tick=tick, time=now,
                                 objective_before=before,
                                 objective_after=after))]
        regressed = False
        if before is not None and after is not None:
            scale = max(abs(before), _EPS)
            # lower is better: positive rel = regression
            rel = (after - before) / scale
            if rel > self.config["revert_margin_frac"]:
                regressed = True
        self._guard.settle()
        if regressed:
            try:
                if self.actuator is not None:
                    self.actuator({knob: pend["from"]})
            except Exception:
                logger.warning("autopilot revert actuation failed for %s",
                               knob, exc_info=True)
            self._values[knob] = pend["from"]
            self._guard.start_cooldown(knob, now, reverted=True)
            out.append(self._record(dict(
                base, stage="reverted", tick=tick, time=now,
                objective_before=before, objective_after=after)))
        else:
            self._guard.start_cooldown(knob, now)
            out.append(self._record(dict(
                base, stage="kept", tick=tick, time=now,
                objective_before=before, objective_after=after)))
        return out

    def _record(self, record):
        record = dict(record, kind="action")
        with self._lock:
            self._actions.append(record)
            del self._actions[:-int(self.config["max_actions"])]
            stage = record["stage"]
            self._counts[stage] = self._counts.get(stage, 0) + 1
        telemetry.get_tracer().instant(
            "autopilot/" + record["stage"], knob=record.get("knob"),
            to=record.get("to"), signal=record.get("signal"))
        logger.info("autopilot %s: %s %r -> %r (%s)", record["stage"],
                    record.get("knob"), record.get("from"),
                    record.get("to"), record.get("signal"))
        self._journal_write(record)
        if self._on_action is not None:
            try:
                self._on_action(record)
            except Exception:
                logger.warning("autopilot on_action callback failed",
                               exc_info=True)
        return record

    # -- read surface (observatory endpoints) ------------------------------

    def actions(self, limit=None):
        """Newest-last copies of the bounded action log."""
        with self._lock:
            out = list(self._actions)
        if limit is not None:
            out = out[-int(limit):]
        return out

    def action_counts(self):
        """``{stage: count}`` — the ``tfos_autopilot_actions_total``
        source."""
        with self._lock:
            return dict(self._counts)

    def knob_values(self):
        """Driver-side shadow of every knob's current value."""
        with self._lock:
            return dict(self._values)

    def status(self):
        """The ``/status`` ``autopilot`` block (also served whole on
        ``/autopilot``)."""
        now = self._clock()
        with self._lock:
            return {
                "dry_run": self.dry_run,
                "ticks": self._ticks,
                "interval_secs": self.config["interval_secs"],
                "window_secs": self.config["window_secs"],
                "knobs": dict(self._values),
                "cooldowns": self._guard.cooldowns(now),
                "pending": (None if self._guard.pending is None
                            else {k: self._guard.pending[k]
                                  for k in ("seq", "knob", "from", "to",
                                            "signal")}),
                "action_counts": dict(self._counts),
                "actions": list(self._actions)[-10:],
                "journal": self.journal_path,
            }

    # -- journal (shared JsonlJournal — see guardrails.py) ------------------

    def _journal_write(self, record):
        self._journal.write(record)

    def _journal_meta(self):
        cfg = {k: v for k, v in self.config.items() if k != "knobs"}
        self._journal_write({
            "kind": "meta", "version": JOURNAL_VERSION,
            "time": self._clock(), "dry_run": self.dry_run,
            "config": cfg,
            "knobs": {name: spec.get("initial")
                      for name, spec in self.config["knobs"].items()},
        })

    def _journal_snapshot(self, now=None, force=False):
        if self.journal_path is None:
            return
        now = self._clock() if now is None else now
        every = self.config["journal_snapshot_secs"]
        if not force and now - self._last_journal_snap < every:
            return
        self._last_journal_snap = now
        snap = None
        if self._snapshot_fn is not None:
            try:
                snap = self._snapshot_fn()
            except Exception:
                snap = None
        if not snap or not snap.get("nodes"):
            return
        self._journal_write({"kind": "snapshot", "time": now,
                             "snapshot": snap})


# -- offline replay ---------------------------------------------------------

read_journal = _read_journal


class _StaticRing(object):
    """Mutable stand-in for SampleRing so replay drives the same
    controller code the live run did."""

    def __init__(self):
        self._series = {}

    def append(self, node, ts, counters):
        self._series.setdefault(str(node), []).append((ts, counters))

    def trim(self, horizon):
        for node in list(self._series):
            self._series[node] = [(ts, c) for ts, c in self._series[node]
                                  if ts >= horizon]

    def series(self):
        return {n: list(s) for n, s in self._series.items()}


def replay_journal(records, config=None):
    """Re-run the decision logic over an autopilot journal exactly as the
    live controller would have — in dry-run, so replay never actuates.

    The journal's ``meta`` record supplies the run's config and initial
    knob values unless overridden; snapshot records rebuild the per-node
    series and the controller is ticked at each snapshot's timestamp.
    Returns::

        {"actions": [...], "journaled_actions": [...],
         "config": {...}, "snapshots": N}

    ``actions`` is the replay-derived stream (all ``proposed`` — dry-run
    never applies); ``journaled_actions`` is what the live run recorded.
    Comparing the two is the live-vs-replay divergence surface
    ``scripts/metrics_replay.py`` prints.
    """
    if isinstance(records, str):
        records = read_journal(records)
    meta_cfg, meta_knobs = {}, {}
    for rec in records:
        if rec.get("kind") == "meta":
            meta_cfg = {k: v for k, v in (rec.get("config") or {}).items()
                        if k in DEFAULT_CONFIG and k != "knobs"}
            meta_knobs = rec.get("knobs") or {}
            break
    merged = dict(meta_cfg, dry_run=True)
    if config:
        merged.update(config)
    merged.setdefault("knobs", {})
    for name, initial in meta_knobs.items():
        if name in DEFAULT_KNOBS and initial is not None:
            merged["knobs"].setdefault(name, {})
            merged["knobs"][name].setdefault("initial", initial)
    journaled = [dict(r) for r in records if r.get("kind") == "action"]
    ring = _StaticRing()
    clock = {"now": 0.0}
    pilot = Autopilot(ring, config=merged, clock=lambda: clock["now"])
    actions = []
    snaps = sorted((r for r in records if r.get("kind") == "snapshot"),
                   key=lambda r: r.get("time", 0))
    for rec in snaps:
        now = rec.get("time", 0.0)
        clock["now"] = now
        for node, counters in ((rec.get("snapshot") or {})
                               .get("nodes") or {}).items():
            if isinstance(counters, dict):
                ring.append(node, now, counters)
        ring.trim(now - 2 * pilot.config["window_secs"])
        actions.extend(pilot.tick(now=now))
    return {"actions": actions, "journaled_actions": journaled,
            "config": pilot.config, "snapshots": len(snaps)}
