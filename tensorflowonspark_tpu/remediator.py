"""Remediator: the topology action plane that closes detect -> act.

The watchtower (PR 9) names the guilty executor and the autopilot
(PR 14) turns scalar knobs, but until now the only remediation path was
the liveness fence -> ``release_slot`` -> ``provision_replacement``
chain, which fires exclusively on outright death — a straggling,
NaN-poisoned, or saturated node degraded the run forever while the
alert log narrated.  This module subscribes to admitted watchtower
alerts (the existing ``on_alert`` bridge) and executes **topology**
actions under the same guardrail vocabulary the autopilot uses for
knobs (:mod:`~tensorflowonspark_tpu.guardrails` — extracted, not
duplicated):

=====================  ==========================  =====================
action                 fired by                    machinery reused
=====================  ==========================  =====================
``evict_straggler``    persistent ``straggler_*``  graceful self-SIGTERM
                                                   (knob command) +
                                                   ``release_slot`` +
                                                   ``provision_replacement``
``rollback_poison``    ``nonfinite`` (crit)        ``train_rollback`` knob
                                                   -> ``PoisonRollback``
                                                   -> ``restore_latest_
                                                   valid`` (poison step
                                                   quarantined
                                                   ``<step>.corrupt``)
``scale_out_workers``  sustained ``dataservice_    spawn ``dataservice_
                       saturation``/``cache_       worker`` subprocesses
                       thrash``                    (dynamic WREG; cache
                                                   affinity absorbs them)
``scale_out_serving``  ``slo_budget_burn``         spawn a gateway
                                                   replica behind the
                                                   roster (AOT-warmed)
=====================  ==========================  =====================

Guardrails, in gating order: **confirm windows** (the watchtower's
``persists_windows`` streak — or the remediator's own, whichever is
larger — must reach the per-action threshold before a proposal is
minted), **one action in flight** (a second action is never considered
while one is settling, so effects stay attributable), **per-family
cooldown** (scale-out and scale-in share a family key, so the pair
cannot flap), **revert-on-regression** where the action is reversible
(a spawned worker/replica is retired when the objective regressed past
``revert_margin_frac``), and **dry-run** (proposes + journals, never
actuates).  Budgets bound every family: ``max_evictions``,
``max_rollbacks``, ``max_workers``, ``max_replicas``; idle windows
scale added workers/replicas back in, detaching cleanly so splits
re-bind.

Every action stage is journaled to a flush-per-write JSONL
(``<log_dir>/remediator/journal.jsonl``; ``proposed -> applied ->
effect -> kept/reverted``), counted into
``tfos_remediation_actions_total{action,stage}``, served on
``GET /remediations``, traced as ``remediator/<stage>`` instants, and
latched into ``tf_status["remediations"]``.  :func:`replay_journal`
re-derives the proposed-action stream offline from the journal's alert
and snapshot records (``scripts/metrics_replay.py --kind remediator``).
See docs/FAULT_TOLERANCE.md ("Self-healing: the remediator").
"""

import inspect
import logging
import math
import os
import signal
import subprocess
import threading
import time

from . import telemetry
from .guardrails import Guardrails, JsonlJournal, STAGES  # noqa: F401
from .watchtower import read_journal, window_deltas

logger = logging.getLogger(__name__)

JOURNAL_VERSION = 1

def _alert_model_labels(alert):
    """``{"model", "version"}`` spawn substitutions off an alert's
    version labels (the watchtower stamps serving alerts with the
    replica's latched ``serving_model``/``serving_model_version``)."""
    if not isinstance(alert, dict):
        return None
    out = {}
    if alert.get("model") is not None:
        out["model"] = alert["model"]
    if alert.get("version") is not None:
        out["version"] = alert["version"]
    return out or None


#: watchtower rule -> action family
RULE_ACTIONS = {
    "nonfinite": "rollback_poison",
    "straggler_step_time": "evict_straggler",
    "straggler_dispatch_gap": "evict_straggler",
    "straggler_infeed": "evict_straggler",
    "dataservice_saturation": "scale_out_workers",
    "cache_thrash": "scale_out_workers",
    # slo_budget_burn superseded latency_slo_burn (PR 19); the old name
    # stays mapped so journal replays of earlier runs still resolve
    "latency_slo_burn": "scale_out_serving",
    "slo_budget_burn": "scale_out_serving",
}

#: decision order within a tick: correctness before capacity
ACTION_PRIORITY = ("rollback_poison", "evict_straggler",
                   "scale_out_workers", "scale_out_serving")

#: scale-out/scale-in pairs share one cooldown family so they cannot flap
COOLDOWN_FAMILY = {
    "evict_straggler": "evict",
    "rollback_poison": "rollback",
    "scale_out_workers": "workers",
    "scale_in_workers": "workers",
    "scale_out_serving": "serving",
    "scale_in_serving": "serving",
}

#: actions whose applied effect can be rolled back (retire what we spawned)
REVERSIBLE = ("scale_out_workers", "scale_out_serving")

DEFAULT_CONFIG = {
    # control tick cadence and the sliding measurement window
    "interval_secs": 1.0,
    "window_secs": 15.0,
    # ticks between actuation and judging its effect
    "settle_ticks": 3,
    # per-family freeze after an action settles (longer after a revert)
    "cooldown_secs": 15.0,
    "revert_cooldown_secs": 60.0,
    # objective regression beyond this relative margin reverts a
    # reversible action (lower-is-better objectives, autopilot contract)
    "revert_margin_frac": 0.25,
    # propose + journal but never actuate
    "dry_run": False,
    # a standing alert older than this no longer justifies an action
    "alert_ttl_secs": 30.0,
    # consecutive watchtower windows (persists_windows, or the
    # remediator's own standing-alert streak) before each family acts —
    # eviction is destructive and waits longest; a crit nonfinite acts
    # on the first alert
    "confirm_windows": {"evict_straggler": 3, "rollback_poison": 1,
                        "scale_out_workers": 2, "scale_out_serving": 2,
                        "scale_in_workers": 1, "scale_in_serving": 1},
    # budgets: how much topology the remediator may change on its own
    "max_evictions": 2,
    "max_rollbacks": 2,
    "max_workers": 2,
    "max_replicas": 1,
    # quiet ticks (no standing alert for the family) before an ADDED
    # worker/replica is retired
    "scale_in_idle_windows": 8,
    # evict-family grace after a replacement is dispatched: the fresh
    # node compiles cold and must not be re-flagged while warming up
    "replacement_grace_secs": 30.0,
    # subprocess argv for the scale-out families; None disables the
    # family unless the wiring injects an actuator directly
    "worker_spawn_argv": None,
    "serving_spawn_argv": None,
    # bounded in-memory action log + journal snapshot cadence
    "max_actions": 64,
    "journal_snapshot_secs": 10.0,
}

_EPS = 1e-9


def _is_num(v):
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def merge_config(config):
    """Key-wise merge over :data:`DEFAULT_CONFIG`; unknown keys raise so a
    typo'd threshold fails loudly.  ``confirm_windows`` merges per-action
    (override one threshold without restating the rest)."""
    cfg = dict(DEFAULT_CONFIG)
    cfg["confirm_windows"] = dict(DEFAULT_CONFIG["confirm_windows"])
    for k, v in (config or {}).items():
        if k not in DEFAULT_CONFIG:
            raise ValueError("unknown remediator config key: %r (known: %s)"
                             % (k, ", ".join(sorted(DEFAULT_CONFIG))))
        if k == "confirm_windows":
            unknown = set(v or {}) - set(DEFAULT_CONFIG["confirm_windows"])
            if unknown:
                raise ValueError("unknown remediator confirm_windows "
                                 "action(s): %s" % sorted(unknown))
            cfg["confirm_windows"].update(v or {})
        else:
            cfg[k] = v
    return cfg


class _SubprocessPool(object):
    """Bookkeeping for the subprocesses a scale-out family spawned: spawn
    appends, retire pops newest-first (the revert contract: undo the
    thing just added), ``stop_all`` is the teardown sweep.  SIGTERM is
    the retire signal — both the ``dataservice_worker`` and gateway CLIs
    install clean-stop handlers that BYE/detach so splits and in-flight
    batches re-bind instead of fencing."""

    def __init__(self, argv, name):
        self.argv = list(argv) if argv else None
        self.name = name
        self._procs = []

    def size(self):
        self.reap()
        return len(self._procs)

    def reap(self):
        """Drop members that already exited (crashed or externally
        stopped) so budgets reflect live capacity."""
        self._procs = [p for p in self._procs if p.poll() is None]

    def spawn(self, subst=None):
        """Launch one member.  ``subst`` (e.g. ``{"model": ...,
        "version": ...}``) is substituted into ``{model}``-style argv
        placeholders, so a serving scale-out provisions capacity for the
        model the alert names — not a hardcoded one.  Placeholders with
        no substitution are left verbatim (an argv without placeholders
        is unchanged)."""
        if not self.argv:
            raise RuntimeError("no spawn argv configured for %s" % self.name)
        argv = self.argv
        if subst:
            class _Keep(dict):
                def __missing__(self, key):
                    return "{" + key + "}"
            safe = _Keep({k: str(v) for k, v in subst.items()
                          if v is not None})
            argv = [a.format_map(safe) if "{" in a else a for a in argv]
        proc = subprocess.Popen(argv)
        self._procs.append(proc)
        return {"pid": proc.pid, "argv": argv, "pool": self.name,
                "size": len(self._procs)}

    def retire_newest(self, timeout=5.0):
        self.reap()
        if not self._procs:
            return None
        proc = self._procs.pop()
        try:
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=timeout)
        except Exception:
            try:
                proc.kill()
            except OSError:
                pass
        return {"pid": proc.pid, "pool": self.name,
                "size": len(self._procs)}

    def stop_all(self, timeout=5.0):
        while self._procs:
            self.retire_newest(timeout=timeout)


class Remediator(object):
    """Driver-side topology action plane over admitted watchtower alerts.

    Args:
      ring: the observatory :class:`~tensorflowonspark_tpu.observatory.SampleRing`
        (anything with ``series()``), used only for the settle-window
        objective measurement — decisions act on alert payloads (the
        watchtower ships structured ``evidence`` exactly so the
        remediator never re-queries the ring to decide).
      actions: actuator callables injected by the wiring (tests inject
        stubs).  Recognized keys — ``evict`` ``fn(executor, alert) ->
        detail`` (fence + release + replace), ``rollback`` ``fn(executor,
        alert) -> detail`` (push the ``train_rollback`` knob),
        ``spawn_worker``/``retire_worker`` and ``spawn_replica``/
        ``retire_replica`` (default to :class:`_SubprocessPool` over the
        configured ``*_spawn_argv``).  A family with no actuator never
        proposes.
      snapshot_fn: journaled periodically so replay has the series.
      config: key-wise overrides of :data:`DEFAULT_CONFIG`.
      journal_path: flush-per-write JSONL; ``None`` disables.
      on_action: optional ``fn(record)`` per journaled action stage.
      clock: injectable time source (tests, replay).
    """

    def __init__(self, ring, actions=None, snapshot_fn=None, config=None,
                 journal_path=None, on_action=None, clock=time.time):
        self.config = merge_config(config)
        self.ring = ring
        self._snapshot_fn = snapshot_fn
        self._on_action = on_action
        self._clock = clock
        self.journal_path = journal_path
        self._journal = JsonlJournal(journal_path, owner="remediator")
        self._last_journal_snap = 0.0
        self.dry_run = bool(self.config["dry_run"])
        self._guard = Guardrails(self.config["cooldown_secs"],
                                 self.config["revert_cooldown_secs"])
        self._workers = _SubprocessPool(self.config["worker_spawn_argv"],
                                        "workers")
        self._replicas = _SubprocessPool(self.config["serving_spawn_argv"],
                                         "serving")
        acts = dict(actions or {})
        acts.setdefault("spawn_worker",
                        (lambda: self._workers.spawn())
                        if self._workers.argv else None)
        acts.setdefault("retire_worker",
                        (lambda: self._workers.retire_newest())
                        if self._workers.argv else None)
        acts.setdefault("spawn_replica",
                        (lambda alert=None:
                         self._replicas.spawn(subst=_alert_model_labels(
                             alert)))
                        if self._replicas.argv else None)
        acts.setdefault("retire_replica",
                        (lambda: self._replicas.retire_newest())
                        if self._replicas.argv else None)
        self._actions_fns = acts
        self._standing = {}   # (action, executor) -> latest alert
        self._evicted = set()
        self._evict_grace_until = 0.0
        self._idle_ticks = {"workers": 0, "serving": 0}
        self._added = {"workers": 0, "serving": 0}
        self._budget_spent = {"evict_straggler": 0, "rollback_poison": 0}
        self._seq = 0
        self._ticks = 0
        self._actions = []    # bounded recent action records
        self._counts = {}     # (action, stage) -> count
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        """Start the control thread (idempotent); returns self."""
        if self._thread is not None:
            return self
        self._journal_meta()
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="tfos-remediator", daemon=True)
        self._thread.start()
        telemetry.get_tracer().instant(
            "remediator/start", dry_run=self.dry_run,
            families=len(set(RULE_ACTIONS.values())))
        return self

    def stop(self):
        """Stop the thread, journal a final snapshot, retire every
        subprocess this plane spawned, close the journal.  Idempotent."""
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
            self._journal_snapshot(force=True)
        self._workers.stop_all()
        self._replicas.stop_all()
        self._journal.close()

    def _loop(self):
        interval = self.config["interval_secs"]
        while not self._stop.wait(interval):
            try:
                self.tick()
            except Exception:  # the remediator must never take the run down
                logger.warning("remediator tick failed", exc_info=True)

    # -- watchtower bridge -------------------------------------------------

    def observe_alert(self, alert):
        """Watchtower ``on_alert`` hook: an admitted alert for a mapped
        rule becomes (or refreshes) the standing alert for its action
        family.  Journaled, so offline replay sees the same stream the
        live plane did.  Unmapped rules are ignored."""
        action = RULE_ACTIONS.get((alert or {}).get("rule"))
        if action is None:
            return
        executor = alert.get("executor")
        with self._lock:
            if action == "evict_straggler" and str(executor) in self._evicted:
                return  # the zombie's drain-out must not re-trigger
            self._standing[(action, str(executor))] = dict(alert)
        self._journal.write(dict(alert, kind="alert"))

    # -- control tick ------------------------------------------------------

    def tick(self, now=None):
        """One control pass; returns the action records journaled this
        tick.  Public so tests and replay drive it directly."""
        now = self._clock() if now is None else now
        with self._lock:
            self._ticks += 1
            tick = self._ticks
            self._expire_standing(now)
        emitted = []
        win = self._measure(now)
        if self._guard.pending is not None:
            emitted.extend(self._judge_pending(win, now, tick))
        else:
            emitted.extend(self._consider(win, now, tick))
        self._journal_snapshot(now=now)
        return emitted

    def _expire_standing(self, now):
        ttl = self.config["alert_ttl_secs"]
        for key in [k for k, a in self._standing.items()
                    if now - a.get("time", now) > ttl]:
            del self._standing[key]

    # -- measurement (settle-window objectives only) -----------------------

    def _measure(self, now):
        window = self.config["window_secs"]
        deltas, gauges, per_node = {}, {}, {}
        span, nodes = 0.0, 0
        series = self.ring.series() if self.ring is not None else {}
        for node, samples in series.items():
            recent = [(ts, c) for ts, c in samples if ts >= now - window]
            wd = window_deltas(recent)
            if wd is not None:
                nodes += 1
                span = max(span, wd["span_secs"])
                per_node[node] = wd
                for k, v in wd["deltas"].items():
                    deltas[k] = deltas.get(k, 0) + v
            for _ts, counters in recent[-5:]:
                for k, v in counters.items():
                    if k.endswith(("_hwm", "_max")) and _is_num(v) \
                            and math.isfinite(v):
                        gauges[k] = max(gauges.get(k, 0), v)
        return {"deltas": deltas, "gauges": gauges, "per_node": per_node,
                "span_secs": span, "nodes": nodes}

    def _objective(self, action, win):
        """Lower-is-better objective per family (the autopilot contract),
        measured around reversible actions to arm revert-on-regression;
        irreversible families return None (their effect is the topology
        change itself)."""
        g = win["gauges"]
        if action in ("scale_out_workers", "scale_in_workers"):
            return g.get("dataservice_queue_sat_pct_max")
        if action in ("scale_out_serving", "scale_in_serving"):
            return g.get("serving_p99_us_max")
        return None

    # -- decision ----------------------------------------------------------

    def _actionable(self, action):
        """The actuator gate: a family with nothing to execute never
        proposes (so a run without a worker argv cannot journal phantom
        scale-outs)."""
        fn = {"evict_straggler": "evict", "rollback_poison": "rollback",
              "scale_out_workers": "spawn_worker",
              "scale_in_workers": "retire_worker",
              "scale_out_serving": "spawn_replica",
              "scale_in_serving": "retire_replica"}[action]
        return self._actions_fns.get(fn) is not None

    def _budget_left(self, action):
        if action == "evict_straggler":
            return self._budget_spent[action] < self.config["max_evictions"]
        if action == "rollback_poison":
            return self._budget_spent[action] < self.config["max_rollbacks"]
        if action == "scale_out_workers":
            return self._added["workers"] < self.config["max_workers"]
        if action == "scale_out_serving":
            return self._added["serving"] < self.config["max_replicas"]
        if action == "scale_in_workers":
            return self._added["workers"] > 0
        if action == "scale_in_serving":
            return self._added["serving"] > 0
        return False

    def _consider(self, win, now, tick):
        with self._lock:
            standing = dict(self._standing)
        by_action = {}
        for (action, executor), alert in standing.items():
            by_action.setdefault(action, []).append(alert)
        for action in ACTION_PRIORITY:
            alerts = by_action.get(action)
            if not alerts:
                continue
            # capacity alerts track idleness per family; any standing
            # alert resets the family's scale-in countdown
            fam = COOLDOWN_FAMILY[action]
            if fam in self._idle_ticks:
                self._idle_ticks[fam] = 0
            if not self._actionable(action) or not self._budget_left(action):
                continue
            if action == "evict_straggler" \
                    and now < self._evict_grace_until:
                continue  # replacement still warming up: do not re-judge
            # newest alert with the deepest persistence wins the slot
            alert = max(alerts, key=lambda a: (
                a.get("persists_windows", 1), a.get("time", 0)))
            streak = max(alert.get("persists_windows", 1),
                         self._guard.bump_streak(
                             (action, str(alert.get("executor")))))
            if self._guard.in_cooldown(fam, now):
                continue
            if streak < self.config["confirm_windows"][action]:
                continue
            return self._act(action, alert, win, now, tick)
        return self._consider_scale_in(win, now, tick)

    def _consider_scale_in(self, win, now, tick):
        """Idle-window scale-in of ADDED capacity: a family with no
        standing alert for ``scale_in_idle_windows`` consecutive ticks
        retires its newest spawn (clean detach — splits re-bind)."""
        for fam, action in (("workers", "scale_in_workers"),
                            ("serving", "scale_in_serving")):
            if not self._budget_left(action) or not self._actionable(action):
                continue
            self._idle_ticks[fam] += 1
            if self._idle_ticks[fam] < self.config["scale_in_idle_windows"]:
                continue
            if self._guard.in_cooldown(fam, now):
                continue
            self._idle_ticks[fam] = 0
            alert = {"rule": "idle", "executor": None,
                     "evidence": {"idle_ticks":
                                  self.config["scale_in_idle_windows"]}}
            return self._act(action, alert, win, now, tick)
        return []

    def _act(self, action, alert, win, now, tick):
        fam = COOLDOWN_FAMILY[action]
        executor = alert.get("executor")
        objective = self._objective(action, win)
        self._seq += 1
        base = {"seq": self._seq, "action": action, "rule": alert.get("rule"),
                "executor": executor, "severity": alert.get("severity"),
                "persists_windows": alert.get("persists_windows"),
                "evidence": alert.get("evidence"),
                "reversible": action in REVERSIBLE, "tick": tick}
        out = [self._record(dict(base, stage="proposed",
                                 objective_before=objective, time=now))]
        self._guard.clear_streak((action, str(executor)))
        with self._lock:
            self._standing.pop((action, str(executor)), None)
        if self.dry_run:
            # dry run: propose + journal, never actuate; cooldown still
            # applies so the journal is a decision stream, not a firehose
            self._guard.start_cooldown(fam, now)
            return out
        try:
            detail = self._execute(action, executor, alert)
        except Exception:
            # actuation failure leaves the action at "proposed" (never
            # "applied" — that stage means the topology really changed)
            logger.warning("remediator actuation failed for %s", action,
                           exc_info=True)
            self._guard.start_cooldown(fam, now)
            return out
        self._account(action, +1)
        self._guard.begin(dict(base, objective_before=objective,
                               applied_tick=tick, applied_time=now,
                               detail=detail))
        out.append(self._record(dict(base, stage="applied", time=now,
                                     objective_before=objective,
                                     detail=detail)))
        return out

    def _execute(self, action, executor, alert):
        fns = self._actions_fns
        if action == "evict_straggler":
            detail = fns["evict"](executor, alert)
            with self._lock:
                self._evicted.add(str(executor))
                # the zombie's remaining alerts are moot
                for key in [k for k in self._standing
                            if k[1] == str(executor)]:
                    del self._standing[key]
            self._evict_grace_until = (self._clock()
                                       + self.config[
                                           "replacement_grace_secs"])
            return detail
        if action == "rollback_poison":
            return fns["rollback"](executor, alert)
        if action == "scale_out_workers":
            return fns["spawn_worker"]()
        if action == "scale_in_workers":
            return fns["retire_worker"]()
        if action == "scale_out_serving":
            # pass the alert when the actuator takes it: its model/version
            # labels steer the spawn argv at the burning model (injected
            # zero-arg test/replay actuators keep working unchanged)
            fn = fns["spawn_replica"]
            try:
                takes_alert = bool(inspect.signature(fn).parameters)
            except (TypeError, ValueError):
                takes_alert = False
            return fn(alert) if takes_alert else fn()
        if action == "scale_in_serving":
            return fns["retire_replica"]()
        raise ValueError("unknown action %r" % action)

    def _account(self, action, delta):
        if action in self._budget_spent:
            self._budget_spent[action] += max(delta, 0)
        elif action == "scale_out_workers":
            self._added["workers"] += delta
        elif action == "scale_in_workers":
            self._added["workers"] -= delta
        elif action == "scale_out_serving":
            self._added["serving"] += delta
        elif action == "scale_in_serving":
            self._added["serving"] -= delta

    def _judge_pending(self, win, now, tick):
        pend = self._guard.pending
        if tick - pend["applied_tick"] < self.config["settle_ticks"]:
            return []
        action = pend["action"]
        fam = COOLDOWN_FAMILY[action]
        before = pend.get("objective_before")
        after = self._objective(action, win)
        base = {k: pend[k] for k in ("seq", "action", "rule", "executor",
                                     "reversible")}
        out = [self._record(dict(base, stage="effect", tick=tick, time=now,
                                 objective_before=before,
                                 objective_after=after,
                                 detail=pend.get("detail")))]
        regressed = False
        if pend["reversible"] and before is not None and after is not None:
            rel = (after - before) / max(abs(before), _EPS)
            if rel > self.config["revert_margin_frac"]:
                regressed = True
        self._guard.settle()
        if regressed:
            try:
                detail = self._execute(
                    {"scale_out_workers": "scale_in_workers",
                     "scale_out_serving": "scale_in_serving"}[action],
                    None, {})
            except Exception:
                logger.warning("remediator revert failed for %s", action,
                               exc_info=True)
                detail = None
            else:
                self._account(action, -1)
            self._guard.start_cooldown(fam, now, reverted=True)
            out.append(self._record(dict(
                base, stage="reverted", tick=tick, time=now,
                objective_before=before, objective_after=after,
                detail=detail)))
        else:
            self._guard.start_cooldown(fam, now)
            out.append(self._record(dict(
                base, stage="kept", tick=tick, time=now,
                objective_before=before, objective_after=after)))
        return out

    def _record(self, record):
        record = dict(record, kind="action")
        with self._lock:
            self._actions.append(record)
            del self._actions[:-int(self.config["max_actions"])]
            key = (record["action"], record["stage"])
            self._counts[key] = self._counts.get(key, 0) + 1
        telemetry.get_tracer().instant(
            "remediator/" + record["stage"], action=record.get("action"),
            rule=record.get("rule"), executor=record.get("executor"))
        logger.warning("remediator %s: %s (rule=%s executor=%s)",
                       record["stage"], record.get("action"),
                       record.get("rule"), record.get("executor"))
        self._journal.write(record)
        if self._on_action is not None:
            try:
                self._on_action(record)
            except Exception:
                logger.warning("remediator on_action callback failed",
                               exc_info=True)
        return record

    # -- read surface (observatory endpoints) ------------------------------

    def actions(self, limit=None):
        """Newest-last copies of the bounded action log."""
        with self._lock:
            out = list(self._actions)
        if limit is not None:
            out = out[-int(limit):]
        return out

    def action_counts(self):
        """``{action: {stage: count}}`` — the
        ``tfos_remediation_actions_total{action,stage}`` source."""
        with self._lock:
            nested = {}
            for (action, stage), n in self._counts.items():
                nested.setdefault(action, {})[stage] = n
            return nested

    def status(self):
        """The ``/status`` ``remediator`` block (also served whole on
        ``/remediations``)."""
        now = self._clock()
        with self._lock:
            standing = [{"action": a, "executor": e,
                         "rule": alert.get("rule"),
                         "persists_windows": alert.get("persists_windows"),
                         "age_secs": round(now - alert.get("time", now), 2)}
                        for (a, e), alert in self._standing.items()]
        pend = self._guard.pending
        return {
            "dry_run": self.dry_run,
            "ticks": self._ticks,
            "interval_secs": self.config["interval_secs"],
            "window_secs": self.config["window_secs"],
            "standing_alerts": standing,
            "cooldowns": self._guard.cooldowns(now),
            "pending": (None if pend is None
                        else {k: pend[k] for k in
                              ("seq", "action", "rule", "executor")}),
            "budgets": {
                "evictions": [self._budget_spent["evict_straggler"],
                              self.config["max_evictions"]],
                "rollbacks": [self._budget_spent["rollback_poison"],
                              self.config["max_rollbacks"]],
                "workers_added": [self._added["workers"],
                                  self.config["max_workers"]],
                "replicas_added": [self._added["serving"],
                                   self.config["max_replicas"]],
            },
            "action_counts": self.action_counts(),
            "actions": self.actions(limit=10),
            "journal": self.journal_path,
        }

    # -- journal -----------------------------------------------------------

    def _journal_meta(self):
        cfg = {k: v for k, v in self.config.items()}
        self._journal.write({
            "kind": "meta", "version": JOURNAL_VERSION,
            "time": self._clock(), "dry_run": self.dry_run,
            "config": cfg,
            # the kind-detection marker metrics_replay.py keys on (an
            # autopilot meta carries "knobs" instead)
            "families": sorted(set(RULE_ACTIONS.values())),
        })

    def _journal_snapshot(self, now=None, force=False):
        if self.journal_path is None:
            return
        now = self._clock() if now is None else now
        every = self.config["journal_snapshot_secs"]
        if not force and now - self._last_journal_snap < every:
            return
        self._last_journal_snap = now
        snap = None
        if self._snapshot_fn is not None:
            try:
                snap = self._snapshot_fn()
            except Exception:
                snap = None
        if not snap or not snap.get("nodes"):
            return
        self._journal.write({"kind": "snapshot", "time": now,
                             "snapshot": snap})


# -- offline replay ---------------------------------------------------------

def replay_journal(records, config=None):
    """Re-run the decision logic over a remediator journal exactly as the
    live plane would have — in dry-run, so replay never actuates.

    The journal's ``meta`` record supplies the run's config unless
    overridden; ``alert`` records re-feed ``observe_alert`` and
    ``snapshot`` records rebuild the measurement series, with the plane
    ticked at each record's timestamp.  Returns::

        {"actions": [...], "journaled_actions": [...],
         "config": {...}, "alerts": N, "snapshots": N}

    ``actions`` is the replay-derived stream (all ``proposed`` — dry-run
    never applies); ``journaled_actions`` is what the live run recorded.
    Comparing the two is the divergence surface
    ``scripts/metrics_replay.py --kind remediator`` prints.
    """
    from .autopilot import _StaticRing

    if isinstance(records, str):
        records = read_journal(records)
    meta_cfg = {}
    for rec in records:
        if rec.get("kind") == "meta":
            meta_cfg = {k: v for k, v in (rec.get("config") or {}).items()
                        if k in DEFAULT_CONFIG}
            break
    merged = dict(meta_cfg, dry_run=True,
                  worker_spawn_argv=None, serving_spawn_argv=None)
    if config:
        merged.update(config)
    journaled = [dict(r) for r in records if r.get("kind") == "action"]
    ring = _StaticRing()
    clock = {"now": 0.0}
    # dry-run still requires the actuator gate to pass, so replay arms
    # every family with inert stubs — a proposal is the terminal stage
    stubs = {k: (lambda *a, **kw: None)
             for k in ("evict", "rollback", "spawn_worker", "retire_worker",
                       "spawn_replica", "retire_replica")}
    plane = Remediator(ring, actions=stubs, config=merged,
                       clock=lambda: clock["now"])
    actions = []
    events = sorted((r for r in records
                     if r.get("kind") in ("alert", "snapshot")),
                    key=lambda r: r.get("time", 0))
    n_alerts = n_snaps = 0
    for rec in events:
        now = rec.get("time", 0.0)
        clock["now"] = now
        if rec.get("kind") == "alert":
            n_alerts += 1
            plane.observe_alert({k: v for k, v in rec.items()
                                 if k != "kind"})
        else:
            n_snaps += 1
            for node, counters in ((rec.get("snapshot") or {})
                                   .get("nodes") or {}).items():
                if isinstance(counters, dict):
                    ring.append(node, now, counters)
            ring.trim(now - 2 * plane.config["window_secs"])
        actions.extend(plane.tick(now=now))
    return {"actions": actions, "journaled_actions": journaled,
            "config": plane.config, "alerts": n_alerts,
            "snapshots": n_snaps}
