"""Per-executor node runtime (reference ``TFSparkNode.py``).

The functions here return closures that run as backend tasks on executors:

- :func:`run`       — the "start job" task: claim a role from the cluster
  template, start the per-executor manager, rendezvous with the driver's
  reservation server, derive the ``jax.distributed`` coordinates (the
  TPU-native replacement for building ``TF_CONFIG``,
  reference ``TFSparkNode.py:264-286``), then invoke the user's
  ``main_fun(args, ctx)`` in the foreground (FILES-mode workers) or a
  background process (SPARK-mode workers, ps-like/evaluator roles).
- :func:`train` / :func:`inference` — "feed job" tasks that push partition
  data into the node's queues with backpressure (reference
  ``TFSparkNode.py:371-502``).
- :func:`shutdown`  — poisons the queues and surfaces late errors
  (reference ``TFSparkNode.py:505-559``).

Roles (cluster template job names, reference ``TFCluster.py:250-264``):
``'chief'`` / ``'master'`` (worker 0 with export duties), ``'worker'``,
``'ps'`` (long-running non-worker role parked on a control queue — kept for
capability parity even though TPU training is synchronous), ``'evaluator'``.
"""

import json
import logging
import multiprocessing
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import traceback
import weakref

from tensorflowonspark_tpu import (fault, manager, marker, reservation,
                                   telemetry, util)

logger = logging.getLogger(__name__)

# Job names that join the shared jax.distributed world and get a process_id.
# ps parks on a control queue and never runs jax.  The evaluator runs jax but
# in its OWN single-process world: it executes a different program than the
# workers (periodic eval over checkpoints, reference
# ``examples/mnist/estimator/mnist_tf.py:109-115``), and a process running a
# different program inside the workers' jax.distributed world would wedge
# every collective while inflating num_processes.
_JAX_JOBS = ("chief", "master", "worker")

# Executor-process-lifetime state (reference "TFSparkNode singleton holder",
# ``TFSparkNode.py:75-89``): keeps the manager handle referenced after the
# start task returns — BaseManager shuts its server down when the handle is
# garbage collected, and the node must outlive the start task in SPARK mode.
_node_state = {}

# Live per-process metrics sources (weakrefs): anything with a flat
# ``counters_snapshot() -> dict`` — DataFeeds (TPUNodeContext.get_data_feed),
# ShardedFeeds (infeed overlap tallies), Trainers (dispatch-gap tallies).
# The heartbeat metrics provider snapshots them so HBEAT payloads carry the
# counters without the source having to know about telemetry.
_feeds = []


def _register_feed(feed):
    """Register a metrics source for this node's heartbeats (weakref; dead
    sources are pruned on the next snapshot).  Idempotent: a source that
    registers on every fit call (the Trainer does, from ``fit_feed``) must
    not appear twice — heartbeat merges SUM across registry entries, so a
    duplicate would double-count its counters, and duplicate
    ``apply_knob`` hooks would double-ack knob pushes."""
    for ref in _feeds:
        if ref() is feed:
            return
    _feeds.append(weakref.ref(feed))


# Live-knob application tallies, merged into the heartbeat counters so the
# driver can see that its KNOB pushes actually landed on this node.
_knob_counters = {"autopilot_knobs_applied": 0}

# Remediator eviction tokens already honoured by this process.  The knob
# coordinator re-broadcasts a push on every heartbeat until drained, and the
# SIGTERM drain takes a couple hundred ms — without the dedupe a second beat
# reply could double-fire the timer.
_evict_tokens = set()


def _evict_self(token):
    """Fence honoured node-side: raise SIGTERM against our own process so
    the installed preemption drain runs (feed drain, chief emergency
    checkpoint, BYE goodbye) — the exact path a real preemption takes, so
    eviction inherits its guarantees.  The in-flight Spark feed task then
    fails retryably in the executor parent and PR 3's re-dispatch moves the
    partitions to surviving executors (exact totals preserved)."""
    logger.warning("remediator eviction (token %s): draining via SIGTERM",
                   token)
    telemetry.get_tracer().instant("remediator/evict_self", token=str(token),
                                   flush=True)
    try:
        os.kill(os.getpid(), signal.SIGTERM)
    except OSError:  # pragma: no cover - process already unwinding
        logger.exception("self-eviction signal failed")


def apply_knobs(knobs):
    """Apply a ``{knob: value}`` dict from an autopilot KNOB push to every
    live source in this process that understands it.

    The registry is the same weakref list the heartbeat metrics walk: any
    registered source exposing ``apply_knob(name, value) -> bool``
    (ShardedFeed, ServiceFeed, DataFeed) gets a chance at each knob; names
    nothing claims are ignored — a training node silently skips
    ``serving_*`` knobs and vice versa.  Returns the number of (source,
    knob) applications that took effect.

    ``remediator_evict`` is intercepted BEFORE the fan-out: it is a
    command to this process (fence + drain + exit), not a tunable any
    feed owns.  The value is a one-shot token (dedupe against heartbeat
    re-broadcast); a short timer lets the beat cycle ack the knob as
    drained before the SIGTERM lands."""
    knobs = dict(knobs or {})
    evict_token = knobs.pop("remediator_evict", None)
    applied = 0
    if evict_token is not None and str(evict_token) not in _evict_tokens:
        _evict_tokens.add(str(evict_token))
        applied += 1
        threading.Timer(0.2, _evict_self, args=(evict_token,)).start()
    for name, value in (knobs or {}).items():
        for ref in list(_feeds):
            feed = ref()
            if feed is None:
                continue
            hook = getattr(feed, "apply_knob", None)
            if hook is None:
                continue
            try:
                if hook(name, value):
                    applied += 1
            except Exception:
                logger.warning("apply_knob(%s) failed on %r", name, feed,
                               exc_info=True)
    if applied:
        _knob_counters["autopilot_knobs_applied"] += applied
        telemetry.get_tracer().instant("autopilot/knobs_applied",
                                       applied=applied,
                                       knobs=",".join(sorted(knobs)))
    return applied


def _knob_reply_handler(reply):
    """``HeartbeatSender(on_reply=...)`` hook: apply any live-knob update
    the driver piggybacked on the beat reply (exactly-once per push — the
    KnobCoordinator marks pushes drained at poll time)."""
    if isinstance(reply, dict) and reply.get("knobs"):
        apply_knobs(reply["knobs"])


def _profile_handler(job_name):
    """The ``on_profile`` capture handler for this node's HeartbeatSender:
    JAX-hosting jobs run device-trace captures fanned out on beat replies
    (:func:`profiling.handle_capture_request`); other roles get None — the
    driver never targets them, and a ps node has no devices to trace."""
    if job_name not in _JAX_JOBS:
        return None
    try:
        from tensorflowonspark_tpu import profiling

        return profiling.handle_capture_request
    except Exception:  # pragma: no cover - stripped envs
        return None


def _node_metrics_provider(mgr, qname="input"):
    """Build the heartbeat metrics provider for this node's user-fn process.

    Merges (all flat JSON dicts; see telemetry.merge_counters):
    - shm-ring consumer-side tallies (this process attaches the rings);
    - every live DataFeed's counters (rows, stall time, wire formats);
    - feeder-side counters published to the manager KV by feed tasks
      (they run in a different process — the executor shell);
    - the input queue's depth high-water mark, sampled per beat.

    Every leg is individually guarded: metrics must never cost a beat.
    """
    hwm = {"queue_depth_hwm": 0}

    def _provider():
        from tensorflowonspark_tpu import shmring

        # Telemetry off: beats stay bare and the driver latches nothing —
        # tf_status["telemetry"] is part of the opt-in plane, not a default.
        if not telemetry.get_tracer().enabled:
            return None
        parts = [shmring.counters_snapshot()]
        if _knob_counters["autopilot_knobs_applied"]:
            parts.append(dict(_knob_counters))
        try:
            # tracer self-telemetry: a nonzero events_dropped means this
            # process's trace files are silently truncated — surfaced as a
            # heartbeat counter so the driver sees it live, not post-mortem
            parts.append(telemetry.get_tracer().counters_snapshot())
        except Exception:
            pass
        for ref in list(_feeds):
            feed = ref()
            if feed is None:
                _feeds.remove(ref)
                continue
            try:
                parts.append(feed.counters_snapshot())
            except Exception:
                pass
        try:
            # profiler-server liveness + per-device memory HWMs: device-plane
            # health riding the same beat as the host-side feed counters
            from tensorflowonspark_tpu import metrics as metrics_mod
            from tensorflowonspark_tpu import profiler as profiler_mod

            parts.append(profiler_mod.server_counters())
            parts.append(metrics_mod.device_memory_counters())
        except Exception:
            pass
        try:
            feeder = mgr.get("feeder_metrics")
            if isinstance(feeder, dict):
                parts.append(feeder)
        except Exception:
            pass
        try:
            depth = mgr.get_queue(qname).qsize()
            if depth > hwm["queue_depth_hwm"]:
                hwm["queue_depth_hwm"] = depth
            # Instantaneous depth next to the high-water mark: the HWM can
            # never come back down, so a live backlog signal (is the queue
            # draining NOW?) needs its own gauge.
            parts.append(dict(hwm, queue_depth_max=depth))
        except Exception:
            pass
        return telemetry.merge_counters(parts)

    return _provider

# ---------------------------------------------------------------------------
# Preemption drain (SIGTERM): a preempted host must stop feed consumption,
# land an emergency checkpoint, and deregister cleanly (BYE reason=preempted)
# instead of dying by heartbeat timeout.  The node wrappers install the
# handler in the process running the user fn; interested parties register
# callbacks (the DataFeed registers its drain in get_data_feed; the trainer's
# supervision registers the emergency save in train.fit_supervised).
# ---------------------------------------------------------------------------

_preempt_event = threading.Event()
_preempt_callbacks = []  # run FIFO: feed drain first, then emergency save


def on_preemption(callback):
    """Register ``callback()`` to run when this process receives SIGTERM
    (preemption).  Callbacks run in registration order inside the signal
    handler, so keep them short and idempotent; after they return the
    handler raises ``SystemExit(0)`` to unwind the user fn cleanly.
    Returns the callback (usable as a decorator)."""
    _preempt_callbacks.append(callback)
    return callback


def remove_preemption_callback(callback):
    """Deregister a preemption callback (no-op if absent)."""
    try:
        _preempt_callbacks.remove(callback)
    except ValueError:
        pass


def preempted():
    """True once this process received a preemption SIGTERM."""
    return _preempt_event.is_set()


def _reset_preemption():
    """Fresh preemption state (a forked node child inherits the parent's
    registrations; tests reuse the module in-process)."""
    global _preempt_event
    _preempt_event = threading.Event()
    del _preempt_callbacks[:]


def _sigterm_drain(signum, frame):
    """SIGTERM handler: run the registered drain callbacks once, then exit
    cleanly.  A second SIGTERM while draining is ignored (schedulers often
    send TERM twice before escalating to KILL)."""
    if _preempt_event.is_set():
        return
    _preempt_event.set()
    logger.warning("SIGTERM received: preemption drain (stopping feed, "
                   "emergency checkpoint, clean BYE)")
    for cb in list(_preempt_callbacks):
        try:
            cb()
        except Exception:
            logger.exception("preemption callback %r failed", cb)
    raise SystemExit(0)


def _install_sigterm_drain():
    """Install the preemption handler; False when impossible (signal
    handlers can only be installed from the main thread — e.g. Spark
    executors run tasks on worker threads, where the preemption story is
    Spark's own task re-land instead)."""
    try:
        signal.signal(signal.SIGTERM, _sigterm_drain)
        return True
    except ValueError:
        logger.info("not on the main thread; SIGTERM preemption drain "
                    "not installed")
        return False


class TPUNodeContext(object):
    """Encapsulates a node's identity & helpers, passed to ``main_fun(args, ctx)``.

    Mirrors the reference's ``TFNodeContext`` (``TFSparkNode.py:32-72``) with
    the TF_CONFIG-era fields replaced by jax.distributed coordinates:

    Attributes:
      executor_id: backend executor ordinal this node runs on.
      job_name: ``'chief'|'master'|'worker'|'ps'|'evaluator'``.
      task_index: index within the job.
      cluster_info: full sorted roster of node metadata dicts.
      cluster_spec: ``{job_name: [host:port, ...]}`` view of the roster.
      default_fs: default filesystem prefix for relative paths.
      working_dir: this executor's working directory.
      mgr: connected per-executor manager (queues + state).
      coordinator_address: ``host:port`` of jax.distributed coordinator
        (process 0's reserved port).
      num_processes / process_id: this node's slot in the jax world
        (``None`` for ps nodes).
    """

    def __init__(self, executor_id, job_name, task_index, cluster_info,
                 default_fs, working_dir, mgr, coordinator_address,
                 num_processes, process_id, data_service=None):
        self.executor_id = executor_id
        self.worker_num = executor_id  # reference-compat alias (TFSparkNode.py:34)
        self.job_name = job_name
        self.task_index = task_index
        self.cluster_info = cluster_info
        self.default_fs = default_fs
        self.working_dir = working_dir
        self.mgr = mgr
        self.coordinator_address = coordinator_address
        self.num_processes = num_processes
        self.process_id = process_id
        # disaggregated-data-service spec from cluster.run(data_service=):
        # {"dispatcher": [host, port]} or None (see get_service_feed)
        self.data_service = data_service

    @property
    def cluster_spec(self):
        spec = {}
        for node in self.cluster_info:
            spec.setdefault(node["job_name"], []).append(
                "{}:{}".format(node["host"], node["port"])
            )
        return spec

    @property
    def num_workers(self):
        """Number of JAX-hosting nodes (reference ``TFSparkNode.py:53``)."""
        return len([n for n in self.cluster_info if n["job_name"] in _JAX_JOBS])

    def is_chief(self):
        return self.process_id == 0

    def initialize_distributed(self):
        """Initialize the multi-host JAX runtime for this node.

        The TPU-native act that replaces consuming ``TF_CONFIG``: every
        JAX-hosting node calls ``jax.distributed.initialize`` with the
        coordinates the rendezvous distributed (SURVEY §2.5).  No-op for
        single-process clusters and for ps nodes.
        """
        from tensorflowonspark_tpu.parallel import mesh as mesh_mod

        # before ANY backend touch: env platform selection must win over
        # plugin sitecustomize config rewrites (see enforce_env_platforms)
        mesh_mod.enforce_env_platforms()
        if self.process_id is None or self.num_processes <= 1:
            return
        import jax

        jax.distributed.initialize(
            coordinator_address=self.coordinator_address,
            num_processes=self.num_processes,
            process_id=self.process_id,
        )

    def get_data_feed(self, train_mode=True, qname_in="input",
                      qname_out="output", input_mapping=None):
        """Return a :class:`~tensorflowonspark_tpu.datafeed.DataFeed` on this
        node's queues (reference ``TFNode.py:86``)."""
        from tensorflowonspark_tpu.datafeed import DataFeed

        feed = DataFeed(self.mgr, train_mode, qname_in, qname_out, input_mapping)
        # On preemption the feed must stop consuming first (before the
        # emergency checkpoint), so feeders unblock instead of pushing into a
        # dying node; drain order is registration order.
        on_preemption(feed.terminate)
        # Expose the feed's counters to the heartbeat metrics provider (the
        # real node module of this process, not the closure's copy — see
        # the _node_state comment in run()).
        import tensorflowonspark_tpu.node as _node_mod

        _node_mod._register_feed(feed)
        return feed

    def get_service_feed(self, files, dispatcher=None, **kwargs):
        """Return a :class:`~tensorflowonspark_tpu.dataservice.ServiceFeed`
        reading ``files`` through the disaggregated data service (the
        FILES-mode analog of :meth:`get_data_feed` when ``cluster.run`` was
        given ``data_service=``).

        ``dispatcher`` overrides the cluster-configured address; remaining
        kwargs pass through to ``ServiceFeed`` (``job_name``, ``mode``,
        ``num_epochs``, ``input_mapping``, ...).  The consumer identity
        defaults to this node's executor id."""
        from tensorflowonspark_tpu import dataservice

        if dispatcher is None:
            if not self.data_service:
                raise ValueError(
                    "no data service configured: pass dispatcher= here or "
                    "data_service= to cluster.run")
            dispatcher = self.data_service["dispatcher"]
        kwargs.setdefault("consumer_id",
                          "executor-{}".format(self.executor_id))
        if self.data_service and self.data_service.get("codecs") is not None:
            # cluster-pinned wire-compression offer (cluster.run data_service
            # spec); an explicit codecs= kwarg still wins
            kwargs.setdefault("codecs", self.data_service["codecs"])
        feed = dataservice.ServiceFeed(dispatcher, files, **kwargs)
        # same lifecycle wiring as get_data_feed: preemption drain stops the
        # network streams, and the feed's dataservice_* counters ride this
        # node's heartbeats into the driver's metrics snapshot
        on_preemption(feed.terminate)
        import tensorflowonspark_tpu.node as _node_mod

        _node_mod._register_feed(feed)
        return feed

    def absolute_path(self, path):
        """Normalize a user path against CWD/default_fs (reference ``TFNode.py:23-58``)."""
        from tensorflowonspark_tpu.datafeed import absolute_path

        return absolute_path(self, path)


def _reserve_free_port():
    """Bind an ephemeral port and hold it (reference ``TFSparkNode.py:239-244``)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("", 0))
    return s, s.getsockname()[1]


def _start_tensorboard(log_dir):
    """Spawn TensorBoard for this cluster if available (reference
    ``TFSparkNode.py:199-225``); returns ``(pid, port)`` or ``(0, 0)``."""
    tb_exec = util.find_in_path(os.environ.get("PATH", ""), "tensorboard")
    if not tb_exec:
        logger.warning("tensorboard not found in PATH; skipping launch")
        return 0, 0
    sock, tb_port = _reserve_free_port()
    sock.close()
    proc = subprocess.Popen(
        [sys.executable, tb_exec, "--logdir=%s" % log_dir, "--port=%d" % tb_port],
        env=os.environ,
    )
    return proc.pid, tb_port


def _sort_key(node):
    """Deterministic roster ordering: chief/master first, then workers,
    evaluator, ps — so process_id 0 is always the chief (reference sorts by
    executor_id, ``TFSparkNode.py:264-276``; we sort by role for a stable
    jax.distributed process numbering)."""
    job_rank = {"chief": 0, "master": 0, "worker": 1, "evaluator": 2, "ps": 3}
    return (job_rank.get(node["job_name"], 4), node["task_index"])


def run(fn, tf_args, cluster_meta, tensorboard=False, log_dir=None,
        queues=("input", "output", "error"), background=False,
        release_port=True, profiler=False, driver_local=False):
    """Build the "start job" task closure (reference ``TFSparkNode.py:121-368``).

    Args:
      fn: user map function ``fn(args, ctx)``.
      tf_args: argparse Namespace or argv list passed through to ``fn``.
      cluster_meta: dict from :func:`tensorflowonspark_tpu.cluster.run` with
        ``id``, ``cluster_template``, ``server_addr``, ``authkey``,
        ``default_fs``, ``num_executors``.
      tensorboard: launch TensorBoard on the chief.
      background: run ``fn`` in a background process (SPARK input mode), so the
        executor's task slot frees up for feed jobs (reference
        ``TFSparkNode.py:310-342``).
      release_port: close the reserved coordinator port right before invoking
        ``fn`` (reference ``TFSparkNode.py:306-308``).
      driver_local: this node runs in a DRIVER thread, not on an executor
        (``cluster.run(driver_ps_nodes=True)``, reference
        ``TFCluster.py:291-309``): skip the executor working-dir handshakes
        (executor-id file, stale-node state file, shm rings) — they belong
        to executor cwds, and the driver's cwd never receives the shutdown
        job that would retire a state file.
    """

    def _mapfn(iterator):
        # The start job parallelizes range(num_executors) with one element per
        # partition; that element is this node's executor id
        # (reference TFCluster.py:312-316, TFSparkNode.py:148).  An elastic
        # REPLACEMENT start task instead carries an explicit assignment dict
        # {executor_id, job_name, task_index}: the fresh executor is not in
        # the original template, and the role it must claim is the dead
        # node's released slot (see cluster.run's _request_replacement).
        executor_id = None
        for item in iterator:
            executor_id = item
        assert executor_id is not None, "start task received an empty partition"
        assignment = None
        if isinstance(executor_id, dict):
            assignment = executor_id
            executor_id = assignment["executor_id"]

        # Claim role from the assignment or the template (reference
        # TFSparkNode.py:148-158).
        if assignment is not None:
            job_name = assignment["job_name"]
            task_index = assignment["task_index"]
        else:
            job_name, task_index = None, -1
            for job, executors in cluster_meta["cluster_template"].items():
                if executor_id in executors:
                    job_name = job
                    task_index = executors.index(executor_id)
                    break
            assert job_name is not None, (
                "executor_id {} not present in cluster template {}".format(
                    executor_id, cluster_meta["cluster_template"])
            )
        logger.info("executor_id=%d assigned role %s:%d%s", executor_id,
                    job_name, task_index,
                    " (replacement)" if assignment is not None else "")
        tracer = telemetry.configure_from_meta(cluster_meta)
        tracer.instant("node/role_assigned", executor_id=executor_id,
                       job_name=job_name, task_index=task_index,
                       replacement=assignment is not None)

        # Apply cluster-level env (TPU/XLA perf knobs, device_info.tpu_env)
        # FIRST: libtpu/XLA read these only when the jax client is created,
        # and everything below (manager fork, user fn) inherits them.
        if cluster_meta.get("executor_env"):
            os.environ.update(cluster_meta["executor_env"])

        # Stale-node detection: if this working dir already hosts a live node
        # from another cluster instance, fail loudly so the scheduler retries
        # elsewhere (reference TFSparkNode.py:166-172).  Driver-local nodes
        # skip the cwd handshakes entirely (see driver_local in run()).
        state_file = os.path.join(os.getcwd(), "cluster_state.json")
        if not driver_local:
            if os.path.exists(state_file):
                with open(state_file) as f:
                    prior = json.load(f)
                if prior.get("cluster_id") != cluster_meta["id"] and prior.get("state") == "running":
                    raise Exception(
                        "A node from cluster {} appears to still be running in {}; "
                        "this executor cannot host two clusters. Ensure previous "
                        "clusters were shut down.".format(prior.get("cluster_id"), os.getcwd())
                    )

            util.write_executor_id(executor_id)

        # Start the per-executor manager BEFORE any jax/TPU initialization so
        # the forked manager server never duplicates a live TPU client
        # (reference TFSparkNode.py:174-185; remote mode for roles the driver
        # must reach directly at shutdown, TFCluster.py:186-192).
        authkey = bytes.fromhex(cluster_meta["authkey"])
        qnames = list(queues)
        with tracer.span("node/manager_start", executor_id=executor_id):
            if job_name in ("ps", "evaluator"):
                if "control" not in qnames:
                    qnames.append("control")
                mgr = manager.start(authkey, qnames, mode="remote")
                addr = list(mgr.address)
                if not addr[0]:
                    addr[0] = util.get_ip_address()
            else:
                mgr = manager.start(authkey, qnames, mode="local")
                addr = mgr.address  # unix socket path (same-host connections only)
            mgr.set("state", "running")
        # Pin the manager handle in the *real* node module of this executor
        # process — not this closure's globals.  The start-task closure is
        # cloudpickled by value, so its reconstructed globals (including any
        # module-level dict captured by value) are garbage collected when the
        # executor loads its next task; GC of the manager handle would
        # finalize (kill) the manager server (BaseManager registers a
        # Finalize).  Importing resolves the genuinely process-global module.
        import tensorflowonspark_tpu.node as _node_mod

        # Keyed by executor id: driver_ps_nodes runs several node closures
        # in ONE process (driver threads) — a single shared key would drop
        # all but the last manager's reference.
        _node_mod._node_state["mgr-{}".format(executor_id)] = mgr
        _node_mod._node_state["cluster_id"] = cluster_meta["id"]
        if not driver_local:
            with open(state_file, "w") as f:
                json.dump({"cluster_id": cluster_meta["id"],
                           "state": "running"}, f)

        # Pre-create the shm-ring feed transports HERE, in the long-lived
        # node process, so the creator's lifetime matches the consumer's.
        # Feed tasks only attach: if a short-lived (non-reused) feed worker
        # created a ring, its exit would unlink it under the consumer and
        # the next feed task would create a second ring with the same name
        # — tokens then promise records that never arrive (the hazard
        # native/shmring.cc's shmring_free contract documents).  Driver-local
        # ps nodes never receive feed jobs, so no rings.
        from tensorflowonspark_tpu import shmring

        if shmring.available() and not driver_local:
            # Only feed-direction queues get a ring: results travel back as
            # plain Chunks (DataFeed.batch_results), and error/control carry
            # single small messages.
            with tracer.span("node/rings", executor_id=executor_id):
                for qn in qnames:
                    if qn not in ("error", "control", "output"):
                        shmring.get_ring(
                            shmring.ring_name(cluster_meta["id"], executor_id,
                                              qn),
                            create=True)

        # TensorBoard on the first worker-like node (reference TFSparkNode.py:199-225).
        tb_pid, tb_port = 0, 0
        if tensorboard and job_name in ("chief", "master", "worker") and task_index == 0:
            tb_pid, tb_port = _start_tensorboard(log_dir or "tensorboard_logs")

        # Per-host jax.profiler server so TensorBoard's profile plugin can
        # capture device traces on demand (SURVEY §5.1 TPU mapping).
        profiler_port = 0
        if profiler and job_name in _JAX_JOBS:
            from tensorflowonspark_tpu import profiler as profiler_mod

            profiler_port = profiler_mod.start_server()

        # Reserve the port this node contributes to the roster.  For process 0
        # it becomes the jax.distributed coordinator port (reference reserved
        # the TF gRPC server port here, TFSparkNode.py:239-244).
        port_sock, port = _reserve_free_port()

        host = util.get_ip_address()
        client = reservation.Client(
            cluster_meta.get("server_addrs") or cluster_meta["server_addr"])
        node_meta = {
            "executor_id": executor_id,
            "host": host,
            "job_name": job_name,
            "task_index": task_index,
            "port": port,
            "addr": addr,
            "authkey": cluster_meta["authkey"],
            "pid": os.getpid(),
            "tb_pid": tb_pid,
            "tb_port": tb_port,
            "profiler_port": profiler_port,
            "working_dir": os.getcwd(),
        }
        # Trace flow across the rendezvous: started here, stepped by the
        # driver on REG admission, ended on this node's first heartbeat —
        # Perfetto then links registration -> admission -> liveness causally
        # across the node/driver process boundary.
        reg_flow = tracer.new_flow_id()
        if reg_flow:
            node_meta["trace_flow"] = reg_flow
            tracer.flow_start("reservation/register_flow", reg_flow,
                              leg="node_register", executor_id=executor_id,
                              job_name=job_name)
        with tracer.span("node/register", executor_id=executor_id,
                         job_name=job_name, task_index=task_index):
            client.register(node_meta)
        with tracer.span("node/await", executor_id=executor_id):
            cluster_info = client.await_reservations(
                timeout=cluster_meta.get("reservation_timeout", 600))
        client.close()
        cluster_info.sort(key=_sort_key)

        # Duplicate-registration sanity check (reference TFSparkNode.py:267-270).
        seen = set()
        for n in cluster_info:
            key = (n["job_name"], n["task_index"])
            if key in seen:
                raise Exception(
                    "Duplicate cluster node {}; executors likely ran multiple "
                    "start tasks. Roster: {}".format(key, cluster_info))
            seen.add(key)

        # Derive jax.distributed coordinates — the TF_CONFIG replacement
        # (reference TFSparkNode.py:278-286; SURVEY §2.5 mapping).
        jax_nodes = [n for n in cluster_info if n["job_name"] in _JAX_JOBS]
        num_processes = len(jax_nodes)
        process_id = None
        for i, n in enumerate(jax_nodes):
            if n["executor_id"] == executor_id:
                process_id = i
                break
        coordinator_address = "{}:{}".format(jax_nodes[0]["host"], jax_nodes[0]["port"])
        tracer.instant("node/cluster_ready", executor_id=executor_id,
                       num_processes=num_processes, process_id=process_id)

        ctx = TPUNodeContext(
            executor_id, job_name, task_index, cluster_info,
            cluster_meta.get("default_fs", "file://"), os.getcwd(), mgr,
            coordinator_address, num_processes, process_id,
            data_service=cluster_meta.get("data_service"),
        )

        if release_port:
            port_sock.close()

        def wrapper_fn(args, context):
            """Invoke the user fn with argv semantics (reference TFSparkNode.py:320-324)."""
            # Warm-start compile plane: runs in the process that actually
            # compiles (the forked background child in SPARK mode, this
            # process in FILES mode), BEFORE the user fn touches jax —
            # replacement nodes re-enter through this same closure, which
            # is what makes warm rejoin automatic.  No-op without a
            # configured cache dir.
            from tensorflowonspark_tpu import compilecache

            compilecache.configure_from_meta(cluster_meta)
            if isinstance(args, list):
                sys.argv = args
            fn(args, context)

        heartbeat_interval = cluster_meta.get("heartbeat_interval", 0)

        def wrapper_fn_background(args, context):
            """Background-process wrapper: route exceptions to the error queue
            (reference TFSparkNode.py:326-332)."""
            multiprocessing.current_process().authkey = authkey
            errq = context.mgr.get_queue("error")
            # The heartbeat lives HERE, in the process executing the user fn:
            # a SIGKILL of training silences the beats even though the
            # executor shell and manager survive — that silence is what the
            # driver's liveness monitor detects.  Clean exits (including
            # user-code exceptions, which travel via the error queue) send
            # BYE so they are not miscounted as deaths.
            hb = reservation.HeartbeatSender(
                cluster_meta.get("server_addrs")
                or cluster_meta["server_addr"], executor_id,
                heartbeat_interval,
                metrics_provider=_node_metrics_provider(context.mgr),
                trace_flow=node_meta.get("trace_flow"),
                on_profile=_profile_handler(context.job_name),
                on_reply=_knob_reply_handler).start()
            # Forked children inherit the parent's preemption registrations;
            # start from a clean slate, then install the SIGTERM drain in the
            # process that actually runs the user fn.
            _reset_preemption()
            _install_sigterm_drain()
            # SIGUSR1 -> flight record (this forked child owns its main
            # thread, so the handler installs; no-op when telemetry is off).
            telemetry.install_sigusr1()
            fault.from_env().arm_preempt_notice()
            tracer = telemetry.get_tracer()
            reason = None
            try:
                with tracer.span("node/user_fn", executor_id=executor_id,
                                 job_name=context.job_name,
                                 task_index=context.task_index):
                    wrapper_fn(args, context)
                reason = "done"
            except Exception:
                try:
                    errq.put(traceback.format_exc())
                except (EOFError, BrokenPipeError, ConnectionError, OSError):
                    # the manager (and with it the error queue) is already
                    # gone — cluster shutdown beat us; the traceback still
                    # goes to the executor log via the raise below, but a
                    # dead reporting channel must not mask it with its own
                    # BrokenPipeError
                    logger.warning("error queue unreachable during "
                                   "shutdown; traceback follows in log")
                raise
            finally:
                if preempted():
                    reason = "preempted"
                hb.stop(reason=reason)
                # Crash-safe flush point: runs on clean completion, on user
                # exceptions, AND on the SIGTERM drain's SystemExit — the
                # trace must survive everything short of SIGKILL.
                tracer.flush()

        if job_name in ("ps", "evaluator") or background:
            # Run the user fn in a child process; ps/evaluator then park this
            # task on the control queue so their executor stays reserved
            # (reference TFSparkNode.py:334-361).  SPARK-mode workers return
            # immediately, freeing the slot for feed jobs.
            p = multiprocessing.get_context("fork").Process(
                target=wrapper_fn_background, args=(tf_args, ctx), daemon=True)
            p.start()
            # Publish the user-fn pid so feeders can fast-fail on a consumer
            # that died instead of burning the whole feed_timeout.
            mgr.set("node_pid", p.pid)
            # The start task returns now (SPARK mode frees the slot for feed
            # jobs): flush the bring-up spans recorded in THIS process — the
            # forked child writes its own trace file.
            tracer.flush()
            if job_name in ("ps", "evaluator"):
                ctrl = mgr.get_queue("control")
                errq = mgr.get_queue("error")
                done = False
                while not done:
                    while not ctrl.empty():
                        msg = ctrl.get(block=True)
                        ctrl.task_done()
                        if msg is None:
                            done = True
                    if not errq.empty():
                        trace = errq.get(block=True)
                        errq.task_done()
                        raise Exception(
                            "Exception in {}:{}:\n{}".format(job_name, task_index, trace))
                    time.sleep(1)
                mgr.set("state", "stopped")
                p.terminate()
        else:
            # FILES-mode worker: run inline; the task slot stays occupied for
            # the duration of training (reference TFSparkNode.py:362-366).
            errq = mgr.get_queue("error")
            mgr.set("node_pid", os.getpid())
            hb = reservation.HeartbeatSender(
                cluster_meta.get("server_addrs")
                or cluster_meta["server_addr"], executor_id,
                heartbeat_interval,
                metrics_provider=_node_metrics_provider(mgr),
                trace_flow=node_meta.get("trace_flow"),
                on_profile=_profile_handler(job_name),
                on_reply=_knob_reply_handler).start()
            _reset_preemption()
            _install_sigterm_drain()
            telemetry.install_sigusr1()
            fault.from_env().arm_preempt_notice()
            reason = None
            try:
                with tracer.span("node/user_fn", executor_id=executor_id,
                                 job_name=job_name, task_index=task_index):
                    wrapper_fn(tf_args, ctx)
                reason = "done"
            except Exception:
                errq.put(traceback.format_exc())
                raise
            finally:
                if preempted():
                    reason = "preempted"
                hb.stop(reason=reason)
                mgr.set("state", "finished")
                tracer.flush()

    return _mapfn


def _get_manager(cluster_info, host, executor_id):
    """Reconnect to the manager of the node on (host, executor_id)
    (reference ``TFSparkNode.py:92-118``)."""
    for node in cluster_info:
        if node["host"] == host and node["executor_id"] == executor_id:
            addr = node["addr"]
            authkey = bytes.fromhex(node["authkey"])
            try:
                m = manager.connect(addr, authkey)
            except (OSError, EOFError) as e:
                raise Exception(
                    "Unable to reach the manager of node {} (role {}:{}) at "
                    "{!r} (exists={}) from pid {} cwd {!r}: {!r}. The node "
                    "process may have died; check its logs.".format(
                        executor_id, node["job_name"], node["task_index"],
                        addr, os.path.exists(str(addr)), os.getpid(),
                        os.getcwd(), e))
            state = m.get("state")
            logger.debug("connected to manager %s state=%s", addr, state)
            return m
    raise Exception(
        "No cluster node found on executor {} of host {}. A data task was "
        "scheduled on an executor that is not part of this cluster; ensure "
        "one task slot per executor and no dynamic allocation.".format(
            executor_id, host))


def train(cluster_info, cluster_meta, qname="input", feed_timeout=600,
          chunk_size=1024, num_epochs=1):
    """Feed-job closure: push partition items into this executor's input queue
    (reference ``TFSparkNode.py:371-438``).

    Items travel in **columnar** :class:`~tensorflowonspark_tpu.marker.ColChunk`
    blocks of ``chunk_size`` (object :class:`~tensorflowonspark_tpu.marker.Chunk`
    fallback for non-uniform rows) so the manager-proxy IPC cost amortizes and
    serialization is a few memcpys, not per-row pickling (the reference's
    per-element hops were its feed ceiling, SURVEY §3.2); backpressure is at
    chunk granularity via the JoinableQueue.

    ``num_epochs > 1`` repeats the partition **executor-side**: the feeder
    caches each packed chunk's serialized bytes on the first pass and re-puts
    them per epoch, so epochs cost zero driver->executor shipping and zero
    re-serialization (the reference re-shipped every epoch from the driver
    via ``sc.union([rdd]*num_epochs)``, reference ``TFCluster.py:88-91``).
    Epoch order is per-partition (P1 P1 P2 P2 ...) rather than the
    reference's per-epoch (P1 P2 P1 P2 ...); with per-step batching this is
    equivalent for training and the driver ships each row exactly once.
    """

    def _train(iterator):
        host = util.get_ip_address()
        executor_id = util.read_executor_id()
        tracer = telemetry.configure_from_meta(cluster_meta)
        mgr = _get_manager(cluster_info, host, executor_id)
        queue = mgr.get_queue(qname)
        state = mgr.get("state")
        if state in ("terminating", "stopped"):
            # Consumer already signalled completion: drain this partition
            # without feeding (reference TFSparkNode.py:393-399).
            logger.info("node state %s; skipping partition", state)
            count = sum(1 for _ in iterator)
            logger.info("skipped %d items", count)
        else:
            # Fast-fail before shipping anything: a consumer that died
            # WITHOUT signalling (SIGKILL leaves state 'running' forever)
            # would otherwise absorb the whole partition and then burn
            # feed_timeout on the drain wait.  The error message is
            # classified retryable, so a supervised train() can re-feed
            # this partition to a surviving node.
            _check_consumer_alive(mgr, executor_id, "before feeding")
            putter = _ChunkPutter(queue, cluster_meta, executor_id, qname,
                                  feed_timeout, cache=(num_epochs > 1))
            try:
                with tracer.span("feed/partition", executor_id=executor_id,
                                 qname=qname):
                    count = _feed_blocks(iterator, putter.put, chunk_size)
                    for _ in range(num_epochs - 1):
                        if mgr.get("state") in ("terminating", "stopped"):
                            break
                        count += putter.reput_cached()
                    _publish_feeder_metrics(mgr, putter)
                    # Wait for the consumer to drain the queue, surfacing
                    # user-code errors and enforcing feed_timeout (reference
                    # TFSparkNode.py:407-418).  The deadline scales with
                    # epochs: executor-side replay drains ALL epochs inside
                    # this one task, where the reference's per-epoch
                    # partition tasks each got their own timeout — a fixed
                    # deadline would spuriously kill healthy multi-epoch
                    # runs on the in-queue (no-shm-ring) path.
                    _join_with_error_check(mgr, queue,
                                           feed_timeout * max(num_epochs, 1),
                                           "feeding",
                                           executor_id=executor_id)
            finally:
                # The feeder's trace must survive a failed join too — the
                # chaos timeline needs the feed span that the kill cut short.
                tracer.flush()
            logger.info("fed %d items to %s queue", count, qname)
        # If the consumer began terminating while we fed, ask the driver to
        # stop scheduling feed partitions (reference TFSparkNode.py:422-434).
        if mgr.get("state") == "terminating":
            client = reservation.Client(
                cluster_meta.get("server_addrs")
                or cluster_meta["server_addr"])
            client.request_stop()
            client.close()
        return [count]

    return _train


def _publish_feeder_metrics(mgr, putter):
    """Accumulate this feed task's counters into the node's manager KV
    (``feeder_metrics``), where the consumer-side heartbeat provider picks
    them up.  Feed tasks are serialized per executor, so read-modify-write
    is race-free; any failure (dead manager mid-chaos) is swallowed —
    metrics never outrank the feed itself."""
    if not telemetry.get_tracer().enabled:
        return
    try:
        prev = mgr.get("feeder_metrics")
        mgr.set("feeder_metrics", telemetry.merge_counters(
            [prev if isinstance(prev, dict) else {},
             putter.counters_delta()]))
    except Exception as e:
        logger.debug("feeder metrics publish failed: %s", e)


def _feed_blocks(iterator, put, chunk_size):
    """Batch an item iterator into ``chunk_size`` blocks through ``put``;
    returns the item count (shared by the train and inference feeders)."""
    count = 0
    block = []
    for item in iterator:
        block.append(item)
        count += 1
        if len(block) >= chunk_size:
            put(block)
            block = []
    if block:
        put(block)
    return count


class _ChunkPutter(object):
    """Sends item blocks the fastest way available: columnar payloads as
    zero-copy framed records through the native shm ring
    (:mod:`~tensorflowonspark_tpu.wire` + ``Ring.put_vectored`` — one
    memcpy per column, no intermediate pickle bytes) with an ordering token
    on the queue; pickled ring records for object chunks and non-framable
    columns; an in-queue chunk when the ring is unavailable / the record is
    oversized (see :mod:`~tensorflowonspark_tpu.shmring`).

    With ``cache=True`` every block's packed chunk (or its pickled bytes,
    when the pickled ring path was taken — framed chunks ARE their own raw
    buffers, so the chunk object is the cache) is retained so
    :meth:`reput_cached` can replay the whole partition without touching
    the source rows again — the executor-side epoch repeat.
    """

    def __init__(self, queue, cluster_meta, executor_id, qname, feed_timeout,
                 cache=False):
        from tensorflowonspark_tpu import fault, shmring, wire

        self._queue = queue
        self._feed_timeout = feed_timeout
        self._cache = [] if cache else None
        # Feeder-side telemetry tallies (always on; plain ints — see the
        # shmring.Ring counters for the rationale).  Published per feed task
        # to the node's manager KV so the consumer-side heartbeat can carry
        # them (the feeder runs in a different process than the user fn).
        self.items = 0
        self.bytes = 0
        # Chaos hook: corrupt_chunk_index flips bytes of the Nth serialized
        # chunk on the ring path (consumer-side unpickle/desync failure).
        self._injector = fault.from_env()
        # Framed columnar records unless TFOS_WIRE_FORMAT=pickle (the A/B
        # knob) or a corruption fault targets this feeder — byte corruption
        # is specified over one serialized stream, i.e. the pickled path.
        self._framed = (wire.enabled() and not (
            self._injector.enabled
            and self._injector.spec.get("corrupt_chunk_index") is not None))
        # Attach-only: the node process created the ring at startup (run());
        # a feed task must never create one, or a recycled Spark worker's
        # exit would unlink it under the live consumer (see run()).  No ring
        # (e.g. a custom qname the node didn't pre-create) falls back to
        # in-queue chunks.
        self._ring = None
        if shmring.available():
            self._ring = shmring.get_ring(
                shmring.ring_name(cluster_meta["id"], executor_id, qname))
        # Ring tallies are process-cumulative (executor processes host many
        # feed tasks); remember the baseline so counters_delta() reports
        # only THIS task's work and the KV accumulation never double counts.
        self._ring_base = ((self._ring.writes, self._ring.writevs)
                           if self._ring is not None else (0, 0))

    def counters_delta(self):
        """This feed task's contribution, as flat telemetry counters."""
        snap = {"feeder_items": self.items, "feeder_bytes": self.bytes}
        if self._ring is not None:
            snap["feeder_ring_writes"] = self._ring.writes - self._ring_base[0]
            snap["feeder_ring_writevs"] = (self._ring.writevs
                                           - self._ring_base[1])
            snap["ring_occupancy_hwm"] = int(self._ring.occupancy_hwm)
        return snap

    def put(self, block):
        chunk = marker.pack_columnar(block)
        n = len(block)
        if chunk is None:
            chunk = marker.Chunk(block)
        data = self._send(chunk, n, data=None)
        self.items += n
        if self._cache is not None:
            # When the pickled ring path was taken, the bytes alone suffice
            # for replay (holding the chunk too would double the partition's
            # resident footprint for the whole feed).  Framed chunks cache
            # as the chunk object — its columns are the raw buffers the
            # replay gather-writes again, so there is nothing cheaper.
            self._cache.append((None if data is not None else chunk, n, data))

    def reput_cached(self):
        """Re-send every cached chunk (one epoch); returns the item count."""
        import pickle

        total = 0
        for chunk, n, data in self._cache or ():
            if chunk is None:
                # Rare fallback: the ring accepted this chunk last epoch but
                # rejects it now (e.g. ring unlinked mid-run) — reconstruct
                # the object for the in-queue path.
                if self._send_bytes(data, n):
                    total += n
                    continue
                chunk = pickle.loads(data)
            self._send(chunk, n, data)
            total += n
        self.items += total
        return total

    def _send_bytes(self, data, n):
        """Ring-path replay of cached bytes; False if the ring refused."""
        if self._ring is not None and self._ring.put_bytes(
                data, timeout_secs=self._feed_timeout):
            self._queue.put(marker.ShmChunk(self._ring.name, n), block=True)
            self.bytes += len(data)
            return True
        return False

    def _send(self, chunk, n, data):
        """Ship one chunk; returns the pickled bytes if the pickled ring
        path was taken (for the epoch-repeat cache), else None (framed and
        in-queue sends cache the chunk object itself)."""
        import pickle

        from tensorflowonspark_tpu import wire

        if self._ring is not None:
            if (self._framed and data is None
                    and isinstance(chunk, marker.ColChunk)):
                parts = wire.encode_chunk(chunk)
                if parts is not None and self._ring.put_vectored(
                        parts, timeout_secs=self._feed_timeout):
                    self._queue.put(
                        marker.ShmChunk(self._ring.name, n,
                                        fmt=wire.WIRE_COLV1), block=True)
                    self.bytes += sum(
                        getattr(p, "nbytes", None) or len(p) for p in parts)
                    return None
                # non-framable columns or an oversized record: pickled path
            if data is None:
                data = pickle.dumps(chunk, protocol=pickle.HIGHEST_PROTOCOL)
            # Ship possibly-corrupted bytes but cache the CLEAN ones: the
            # injected fault models one bad transfer, not a poisoned cache.
            payload = self._injector.corrupt(data)
            if self._ring.put_bytes(payload, timeout_secs=self._feed_timeout):
                self._queue.put(marker.ShmChunk(self._ring.name, n),
                                block=True)
                self.bytes += len(payload)
                return data
        self._queue.put(chunk, block=True)
        return None


def _check_consumer_alive(mgr, executor_id, when):
    """Raise (retryably) if the node's user-fn process is gone.

    ``node_pid`` is published by the start task; feeder and node are
    same-host by construction (the feed task reached this executor via the
    working-dir handshake), so a 0-signal probe is authoritative.  A missing
    pid (old node, driver-local) just skips the check.
    """
    pid = mgr.get("node_pid")
    if not pid:
        return
    dead = False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        dead = True
    except OSError:
        return  # EPERM etc.: process exists but isn't ours — treat as alive
    if not dead:
        # The pid exists, but a SIGKILLed node child is a ZOMBIE, not gone:
        # it's a daemon fork whose spawning start task returned long ago, so
        # nothing in the executor reaps it and the 0-signal probe keeps
        # succeeding for the rest of the executor's life.
        try:
            with open("/proc/{}/stat".format(pid)) as f:
                dead = f.read().split(")")[-1].split()[0] == "Z"
        except OSError:
            pass  # no procfs (non-Linux): existence is the best signal
    if dead:
        raise Exception(
            "node process (pid {}) on executor {} died {} — it exited "
            "without consuming its data; check executor logs.".format(
                pid, executor_id, when))


def _join_with_error_check(mgr, queue, timeout, phase, executor_id=None):
    """``queue.join()`` with error-queue polling + timeout (reference
    ``TFSparkNode.py:407-418``); also fails fast when the consumer process
    itself died (an unannounced death would otherwise cost the full
    ``timeout`` to diagnose)."""
    import threading

    joined = threading.Event()

    def _join():
        try:
            queue.join()
        except (EOFError, ConnectionError, BrokenPipeError):
            # Manager went away (executor died mid-feed); the error-queue
            # poll below surfaces the real failure — don't dump this
            # daemon thread's traceback on top of it.
            return
        joined.set()

    t = threading.Thread(target=_join, daemon=True)
    t.start()
    deadline = time.time() + timeout
    errq = mgr.get_queue("error")

    def _surface_user_error():
        if errq.empty():
            return
        # Peek-and-requeue so later lifecycle checks (shutdown's
        # late-error pass) still observe the failure (reference
        # TFSparkNode.py:547-553 applies the same trick).
        trace = errq.get(block=True)
        errq.task_done()
        errq.put(trace)
        raise Exception("Exception in user code during {}:\n{}".format(phase, trace))

    last_pid_check = 0.0
    while not joined.is_set():
        _surface_user_error()
        now = time.time()
        if now - last_pid_check >= 1.0:
            last_pid_check = now
            # Checked AFTER the error queue: a consumer that raised and
            # exited must surface its traceback, not a generic death.
            try:
                _check_consumer_alive(mgr, executor_id,
                                      "while draining the {} queue".format(phase))
            except Exception:
                # The death verdict races the dying consumer's own
                # traceback: its errq.put RPC returns once the item is in
                # the manager's feeder-thread buffer, where empty() (a pipe
                # poll) can't see it yet — so the process may look dead
                # while its traceback is still in flight.  Give the
                # traceback a beat to land; it is the better diagnosis
                # (user-code errors are fatal, a bare death is retryable).
                grace = time.time() + 2.0
                while time.time() < grace:
                    _surface_user_error()
                    time.sleep(0.1)
                raise
        if now > deadline:
            mgr.set("state", "stopped")
            raise Exception(
                "Timeout ({}s) waiting for the consumer to drain the {} queue. "
                "The training process may have exited without consuming its "
                "data; check executor logs.".format(timeout, phase))
        time.sleep(0.1)


def inference(cluster_info, cluster_meta, qname_in="input", qname_out="output",
              feed_timeout=600, chunk_size=1024):
    """Inference feed-job closure: push one partition, await exactly one result
    per input item (reference ``TFSparkNode.py:441-502``)."""

    def _inference(iterator):
        host = util.get_ip_address()
        executor_id = util.read_executor_id()
        tracer = telemetry.configure_from_meta(cluster_meta)
        mgr = _get_manager(cluster_info, host, executor_id)
        queue_in = mgr.get_queue(qname_in)

        putter = _ChunkPutter(queue_in, cluster_meta, executor_id, qname_in,
                              feed_timeout)
        try:
            with tracer.span("feed/partition", executor_id=executor_id,
                             qname=qname_in, mode="inference"):
                count = _feed_blocks(iterator, putter.put, chunk_size)
                _publish_feeder_metrics(mgr, putter)
                # Signal end-of-partition so DataFeed can align result batches
                # (reference TFSparkNode.py:469, marker.py).
                queue_in.put(marker.EndPartition(), block=True)
                if count == 0:
                    return []
                _join_with_error_check(mgr, queue_in, feed_timeout,
                                       "inference feeding",
                                       executor_id=executor_id)
        finally:
            tracer.flush()

        # Collect exactly `count` results: the 1:1 input/output contract
        # (reference TFSparkNode.py:491-500, TFNode.py:160-162).
        queue_out = mgr.get_queue(qname_out)
        results = []
        while count > 0:
            result = queue_out.get(block=True)
            queue_out.task_done()
            if isinstance(result, marker.Chunk):
                results.extend(result.items)
                count -= len(result.items)
            else:
                results.append(result)
                count -= 1
        return results

    return _inference


def shutdown(cluster_info, cluster_meta, queues=("input",), grace_secs=0):
    """Shutdown-job closure: kill TensorBoard, poison the queues, surface late
    errors (reference ``TFSparkNode.py:505-559``)."""

    def _shutdown(iterator):
        host = util.get_ip_address()
        executor_id = util.read_executor_id()
        mgr = _get_manager(cluster_info, host, executor_id)

        for node in cluster_info:  # kill TB on this node (reference 522-528)
            if node["host"] == host and node["executor_id"] == executor_id:
                if node.get("tb_pid"):
                    try:
                        os.kill(node["tb_pid"], 15)
                    except OSError:
                        pass

        # Poison only the data queues: 'error' must stay clean for the
        # late-error check below and 'control' is signalled by the driver
        # (reference TFCluster.py:172-174 passes only data queues here).
        data_queues = [q for q in queues if q not in ("error", "control")]
        logger.info("shutting down node %d: poisoning queues %s", executor_id, data_queues)
        for qname in data_queues:
            try:
                queue = mgr.get_queue(qname)
                queue.put(None, block=True)  # end-of-feed marker (reference 530-540)
            except (AttributeError, EOFError):
                pass

        if grace_secs > 0:
            # Give the chief time to finish exporting (reference 542-545).
            time.sleep(grace_secs)

        # Late-error check: peek-and-requeue so a retried shutdown task still
        # sees the failure (reference TFSparkNode.py:547-553).
        errq = mgr.get_queue("error")
        if not errq.empty():
            trace = errq.get(block=True)
            errq.task_done()
            errq.put(trace)
            raise Exception("Exception in user code:\n{}".format(trace))

        mgr.set("state", "stopped")

        # Remove this executor's shm-ring transports (payload fast path,
        # shmring.py); mappings held by live processes stay valid.
        from tensorflowonspark_tpu import shmring

        if shmring.available():
            for qn in queues:
                shmring.unlink(
                    shmring.ring_name(cluster_meta["id"], executor_id, qn))

        state_file = os.path.join(os.getcwd(), "cluster_state.json")
        if os.path.exists(state_file):
            with open(state_file, "w") as f:
                json.dump({"cluster_id": cluster_meta["id"], "state": "stopped"}, f)
        # Report which node this task actually reached: scheduling does not
        # guarantee one task per executor, so the driver retries until every
        # worker node confirms (poisoning is idempotent — an extra None in a
        # drained queue is harmless).
        return [executor_id]

    return _shutdown
