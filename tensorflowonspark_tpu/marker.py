"""Queue sentinel markers (reference ``marker.py:11-18``).

Items placed in the per-executor data queues alongside real records:

- ``None``            — end-of-feed: no more data will ever arrive (reference
                        convention, ``TFNode.py:129-134``).
- ``EndPartition``    — end of one input partition; used by inference feeding so
                        result batches align with partition boundaries
                        (reference ``TFSparkNode.py:469``, ``TFNode.py:135-140``).
"""


class Marker(object):
    """Base class for out-of-band markers placed in data queues."""


class EndPartition(Marker):
    """Marks the end of one input partition within the feed queue."""
