"""Queue sentinel markers (reference ``marker.py:11-18``).

Items placed in the per-executor data queues alongside real records:

- ``None``            — end-of-feed: no more data will ever arrive (reference
                        convention, ``TFNode.py:129-134``).
- ``EndPartition``    — end of one input partition; used by inference feeding so
                        result batches align with partition boundaries
                        (reference ``TFSparkNode.py:469``, ``TFNode.py:135-140``).
"""


class Marker(object):
    """Base class for out-of-band markers placed in data queues."""


class EndPartition(Marker):
    """Marks the end of one input partition within the feed queue."""


class Chunk(Marker):
    """A block of consecutive items travelling as ONE queue element.

    TPU-first addition: the reference paid one manager-proxy round trip per
    example (the InputMode.SPARK throughput ceiling, SURVEY §3.2); feeders
    here put :class:`Chunk` blocks so the per-element IPC cost amortizes over
    ``len(items)``.  :class:`~tensorflowonspark_tpu.datafeed.DataFeed`
    unpacks chunks transparently — consumers still see items.
    """

    __slots__ = ("items",)

    def __init__(self, items):
        self.items = items


class ShmChunk(Marker):
    """Ordering token for a chunk whose payload travels through the native
    shared-memory ring (:mod:`~tensorflowonspark_tpu.shmring`) instead of
    the manager socket.  The token keeps the JoinableQueue semantics
    (ordering, backpressure, join/fail-fast) while the bytes take the fast
    path; ``count`` is the number of items in the ring record and ``fmt``
    names the in-ring record encoding so the consumer knows how to read it:
    :data:`~tensorflowonspark_tpu.wire.WIRE_PICKLE` (pickled chunk object)
    or :data:`~tensorflowonspark_tpu.wire.WIRE_COLV1` (self-describing
    zero-copy columnar frame, read via the two-phase peek/consume path).
    """

    __slots__ = ("ring_name", "count", "fmt")

    def __init__(self, ring_name, count, fmt="pickle"):
        self.ring_name = ring_name
        self.count = count
        self.fmt = fmt


class ColChunk(Marker):
    """A block of rows stored **columnar**: one contiguous ndarray per field.

    TPU-first: a block of N ``(ndarray, scalar, ...)`` rows pickles as N
    small objects with per-object overhead and unpickles back into N objects
    the consumer must re-stack; the same block as a few contiguous ndarrays
    pickles/unpickles as a handful of memcpys and feeds straight into
    columnar batch assembly (``DataFeed.next_batch_arrays`` concatenates
    column slices — no per-row Python objects anywhere on the hot path).

    ``columns``: tuple of ndarrays, all sharing leading dim ``count``.
    ``tuple_rows``: True when the original rows were tuples/lists of fields
    (``row(i) == tuple(col[i] for col in columns)``); False when rows were
    single values (``row(i) == columns[0][i]``).
    """

    __slots__ = ("columns", "count", "tuple_rows")

    def __init__(self, columns, count, tuple_rows):
        self.columns = columns
        self.count = count
        self.tuple_rows = tuple_rows

    def row(self, i):
        """Materialize row ``i`` (compat path for the item-list API)."""
        if self.tuple_rows:
            return tuple(col[i] for col in self.columns)
        return self.columns[0][i]


def pack_columnar(block):
    """Pack a list of rows into a :class:`ColChunk`, or return ``None`` when
    the rows aren't uniformly shaped numeric fields (caller falls back to a
    plain object :class:`Chunk`).

    Row semantics live in :mod:`~tensorflowonspark_tpu.columnar` (the one
    shared contract for this packer, the DataFeed degraded path, and
    FileFeed); this is the soft (``strict=False``) caller.
    """
    from tensorflowonspark_tpu import columnar

    if not block:
        return None
    res = columnar.rows_to_fields(block, strict=False)
    if res is None:
        return None
    fields, tuple_rows = res
    return ColChunk(fields, len(block), tuple_rows)
