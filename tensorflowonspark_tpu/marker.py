"""Queue sentinel markers (reference ``marker.py:11-18``).

Items placed in the per-executor data queues alongside real records:

- ``None``            — end-of-feed: no more data will ever arrive (reference
                        convention, ``TFNode.py:129-134``).
- ``EndPartition``    — end of one input partition; used by inference feeding so
                        result batches align with partition boundaries
                        (reference ``TFSparkNode.py:469``, ``TFNode.py:135-140``).
"""


class Marker(object):
    """Base class for out-of-band markers placed in data queues."""


class EndPartition(Marker):
    """Marks the end of one input partition within the feed queue."""


class Chunk(Marker):
    """A block of consecutive items travelling as ONE queue element.

    TPU-first addition: the reference paid one manager-proxy round trip per
    example (the InputMode.SPARK throughput ceiling, SURVEY §3.2); feeders
    here put :class:`Chunk` blocks so the per-element IPC cost amortizes over
    ``len(items)``.  :class:`~tensorflowonspark_tpu.datafeed.DataFeed`
    unpacks chunks transparently — consumers still see items.
    """

    __slots__ = ("items",)

    def __init__(self, items):
        self.items = items


class ShmChunk(Marker):
    """Ordering token for a chunk whose payload travels through the native
    shared-memory ring (:mod:`~tensorflowonspark_tpu.shmring`) instead of
    the manager socket.  The token keeps the JoinableQueue semantics
    (ordering, backpressure, join/fail-fast) while the bytes take the fast
    path; ``count`` is the number of items in the ring record.
    """

    __slots__ = ("ring_name", "count")

    def __init__(self, ring_name, count):
        self.ring_name = ring_name
        self.count = count
