"""Throughput / step-time / MFU instrumentation.

First-class equivalent of the reference's ``TimeHistory`` callback and
``build_stats`` summary (reference ``examples/resnet/common.py:177-245``):
per-N-step wall-clock logging, ``avg_exp_per_second``, and final stats —
plus MFU (model FLOPs utilization), which the BASELINE targets are defined
in terms of (BASELINE.md: >=50% MFU on v5e-16).
"""

import json
import logging
import sys
import time

logger = logging.getLogger(__name__)

# Peak dense (bf16) FLOPs per chip for MFU accounting, keyed on the FULL
# lowercased ``device_kind`` string (exact match, not prefix: "tpu v5"
# must never swallow "tpu v5 lite" — a silent 2.3x MFU error).
PEAK_FLOPS = {
    "tpu v2": 46e12,
    "tpu v3": 123e12,
    "tpu v4": 275e12,
    "tpu v5 lite": 197e12,   # v5e: 197 TFLOP/s bf16 (394 is the int8 figure)
    "tpu v5e": 197e12,
    "tpu v5": 459e12,        # v5p reports plain "TPU v5" on some stacks
    "tpu v5p": 459e12,
    "tpu v6 lite": 918e12,   # v6e / trillium
    "tpu v6e": 918e12,
    "cpu": 1e11,             # nominal figure so tests exercise the math
}

# Peak HBM bytes/s per chip for roofline accounting — same keying rules as
# PEAK_FLOPS (full lowercased ``device_kind``, exact match).  Together the
# two tables define the ridge point peak_flops/peak_bw: a step fn whose
# arithmetic intensity (flops / bytes accessed) sits below it is
# memory-bound and its honest ceiling is bw * intensity, not peak flops.
PEAK_BYTES_PER_SEC = {
    "tpu v2": 700e9,
    "tpu v3": 900e9,
    "tpu v4": 1228e9,
    "tpu v5 lite": 819e9,
    "tpu v5e": 819e9,
    "tpu v5": 2765e9,        # v5p
    "tpu v5p": 2765e9,
    "tpu v6 lite": 1640e9,   # v6e / trillium
    "tpu v6e": 1640e9,
    "cpu": 5e10,             # nominal figure so tests exercise the math
}


# Step-time histogram bucket upper bounds, in milliseconds.  Shared by the
# Trainer's runtime accountant (``step_ms_le_<bound>`` heartbeat counters)
# and the observatory's Prometheus rendering (``tfos_step_ms_bucket{le=}``),
# so the two never disagree on bucket edges.  Roughly log-spaced from a
# sub-millisecond CPU toy step to a multi-second pathological stall.
STEP_MS_BUCKETS = (1, 2, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000)

# Serving latency histogram bucket upper bounds, in microseconds.  Shared by
# the gateway's request-plane accountant (``serving_*_us_le_<bound>``
# heartbeat counters for the queue/coalesce/dispatch/serialize stages plus
# the end-to-end ``serving_latency_us`` family) and the observatory's
# Prometheus rendering, mirroring the STEP_MS_BUCKETS contract above.
# Log-spaced from a 50us in-process hit to a 1s pathological stall.
SERVING_US_BUCKETS = (50, 100, 250, 500, 1000, 2500, 5000, 10000, 25000,
                      50000, 100000, 250000, 500000, 1000000)


def achieved_flops_per_sec(step_flops, step_seconds):
    """Achieved per-device FLOP/s for one dispatch (None when unknowable)."""
    if not step_flops or not step_seconds or step_seconds <= 0:
        return None
    return step_flops / step_seconds


def mfu_from_step_time(step_flops, step_seconds):
    """MFU for one step from per-device FLOPs and wall seconds.

    The exact formula :meth:`TimeHistory.mfu` applies (per-device FLOPs over
    per-device peak over step seconds) — exposed standalone so the runtime
    accountant (``train.Trainer``) and the bench scripts compute the same
    number from the same inputs.
    """
    peak = peak_flops_per_device()
    if peak is None or not step_flops or not step_seconds or step_seconds <= 0:
        return None
    return step_flops / peak / step_seconds


def compression_ratio(raw_bytes, wire_bytes):
    """Wire compression ratio ``raw / wire`` (> 1 when the codec saved
    bytes; 1.0 when nothing compressed or either side is unknown, so
    gauges and bench stats never divide by zero).  The one definition
    shared by ``ServiceFeed.counters_snapshot``, the bench
    ``dataservice_cached_epoch`` leg, and ``profile_feed.py`` — the same
    single-formula contract as :func:`mfu_from_step_time`."""
    if not raw_bytes or not wire_bytes or wire_bytes <= 0:
        return 1.0
    return raw_bytes / float(wire_bytes)


def peak_flops_per_device():
    import jax

    kind = getattr(jax.devices()[0], "device_kind", "cpu").lower()
    val = PEAK_FLOPS.get(kind)
    if val is None:
        logger.warning(
            "unknown device kind %r; MFU will be reported as None", kind)
    return val


def peak_bytes_per_sec_per_device():
    import jax

    kind = getattr(jax.devices()[0], "device_kind", "cpu").lower()
    val = PEAK_BYTES_PER_SEC.get(kind)
    if val is None:
        logger.warning(
            "unknown device kind %r; roofline will be reported as None", kind)
    return val


def estimate_step_flops(jitted_fn, *args, **kwargs):
    """Per-device FLOPs of one compiled step from XLA's cost analysis
    (falls back to None).

    XLA reports the cost of the post-SPMD-partitioning per-device module, so
    on an N-device mesh this is ~1/N of the global step FLOPs — pair it with
    the per-device peak (see :meth:`TimeHistory.mfu`)."""
    return estimate_step_cost(jitted_fn, *args, **kwargs)["flops"]


def estimate_step_cost(jitted_fn, *args, **kwargs):
    """Cost-analyze one compiled step: per-device FLOPs, bytes accessed,
    and the lower+compile wall time.

    Returns ``{"flops": float|None, "bytes_accessed": float|None,
    "compile_secs": float}``.  ``bytes accessed`` (the XLA key has a space)
    is the cost model's total HBM traffic for the per-device module — the
    denominator of the arithmetic intensity :func:`roofline` classifies on.
    Both figures fall back to None when the backend has no cost model;
    ``compile_secs`` is always real (it times the lower+compile even on a
    failure path, where it reports the time spent failing)."""
    t0 = time.perf_counter()
    try:
        compiled = jitted_fn.lower(*args, **kwargs).compile()
        compile_secs = time.perf_counter() - t0
        cost = compiled.cost_analysis()
        if isinstance(cost, list):  # older jax returns [dict]
            cost = cost[0]
        return {
            "flops": float(cost.get("flops", 0.0)) or None,
            "bytes_accessed": float(cost.get("bytes accessed", 0.0)) or None,
            "compile_secs": compile_secs,
        }
    except Exception:
        logger.warning("cost analysis unavailable", exc_info=True)
        return {"flops": None, "bytes_accessed": None,
                "compile_secs": time.perf_counter() - t0}


def roofline(step_flops, bytes_accessed, peak_flops=None, peak_bps=None):
    """Roofline classification of one step fn.

    Args are per-device figures (XLA cost analysis reports the partitioned
    module).  ``peak_flops``/``peak_bps`` default to the local device's
    table entries.  Returns None when any input is unknowable, else::

        {"arithmetic_intensity": flops/byte,
         "ridge_point":          peak_flops / peak_bps (flops/byte),
         "bound":                "memory" | "compute",
         "ceiling_flops_per_sec": min(peak_flops, intensity * peak_bps),
         "ideal_step_seconds":   step_flops / ceiling}

    ``ideal_step_seconds`` is the time the device MUST spend on this step
    at the roofline ceiling — the device-compute bucket of the attribution
    report; everything a measured step takes beyond it is starvation,
    drain, collective time, or device inefficiency.
    """
    if peak_flops is None:
        peak_flops = peak_flops_per_device()
    if peak_bps is None:
        peak_bps = peak_bytes_per_sec_per_device()
    if not step_flops or not bytes_accessed or not peak_flops or not peak_bps:
        return None
    intensity = step_flops / bytes_accessed
    ridge = peak_flops / peak_bps
    ceiling = min(peak_flops, intensity * peak_bps)
    return {
        "arithmetic_intensity": intensity,
        "ridge_point": ridge,
        "bound": "memory" if intensity < ridge else "compute",
        "ceiling_flops_per_sec": ceiling,
        "ideal_step_seconds": step_flops / ceiling,
    }


def device_memory_counters():
    """Per-device peak-memory high-water marks as heartbeat counters.

    Reads ``device.memory_stats()`` across local devices; the max over
    devices of ``bytes_in_use`` and ``peak_bytes_in_use`` land as
    ``device_mem_bytes_in_use_hwm`` / ``device_mem_peak_bytes_hwm``
    (``_hwm`` suffix -> merged by max, rendered as gauges).  Backends
    without memory stats (CPU) contribute ``{}`` — callers must not rely
    on the keys existing.

    This runs on the heartbeat thread, so it must never be the thing that
    pays JAX startup: importing jax (~0.5s) or first-touch backend init
    (seconds on TPU) would stall the beat past the liveness tolerance and
    fence a healthy node.  Processes that never initialized JAX contribute
    ``{}``; ones that did (the trainer) get stats for free."""
    out = {}
    try:
        jax = sys.modules.get("jax")
        if jax is None:
            return out
        xb = sys.modules.get("jax._src.xla_bridge")
        if xb is None or not getattr(xb, "_backends", None):
            return out  # no backend up yet; local_devices() would init one

        in_use, peak = 0, 0
        seen = False
        for dev in jax.local_devices():
            stats = getattr(dev, "memory_stats", lambda: None)()
            if not isinstance(stats, dict):
                continue
            seen = True
            in_use = max(in_use, int(stats.get("bytes_in_use", 0)))
            peak = max(peak, int(stats.get("peak_bytes_in_use",
                                           stats.get("bytes_in_use", 0))))
        if seen:
            out["device_mem_bytes_in_use_hwm"] = in_use
            out["device_mem_peak_bytes_hwm"] = peak
    except Exception:  # metrics must never cost a heartbeat
        logger.debug("device memory stats unavailable", exc_info=True)
    return out


#: Attribution bucket names, in report order.  The buckets decompose one
#: measured wall duration on the step loop and always sum to 100%.
ATTRIBUTION_BUCKETS = ("device_compute", "collective", "infeed_starved",
                       "ckpt_drain", "unattributed")


def attribute_step_time(measured_us, device_compute_us, collective_us=0.0,
                        infeed_starved_us=0.0, ckpt_drain_us=0.0):
    """Decompose ``measured_us`` of step-loop wall time into percentage
    buckets that sum to exactly 100.

    ``device_compute_us`` is the roofline-ideal device time
    (steps * :func:`roofline` ``ideal_step_seconds``); ``collective_us``
    estimated communication time; ``infeed_starved_us``/``ckpt_drain_us``
    the goodput counters.  The remainder is ``unattributed`` — device
    inefficiency plus host overhead the other buckets can't see.  When the
    named buckets overshoot the measurement (clock skew, an optimistic
    collective model) they are scaled down proportionally so the report
    never claims more than 100% of the wall.  Returns None when
    ``measured_us`` is not positive."""
    measured = float(measured_us)
    if measured <= 0:
        return None
    named = [max(float(v), 0.0) for v in (device_compute_us, collective_us,
                                          infeed_starved_us, ckpt_drain_us)]
    total_named = sum(named)
    if total_named > measured:
        scale = measured / total_named
        named = [v * scale for v in named]
        total_named = measured
    parts = named + [measured - total_named]
    return {"%s_pct" % name: 100.0 * v / measured
            for name, v in zip(ATTRIBUTION_BUCKETS, parts)}


class TimeHistory(object):
    """Per-N-step timing + throughput recorder (reference ``common.py:177``).

    Call :meth:`on_step_end(value)` once per global step, passing a device
    value data-dependent on that step (the loss).  Timestamps of each
    N-step window land in ``timestamp_log`` exactly like the reference's
    Keras callback, so ``avg_examples_per_second`` is computed the same way
    (reference ``common.py:236-244``).

    Timing discipline: jax dispatch is asynchronous — the host returns from
    a jitted call long before the device finishes, so timestamping the host
    clock alone measures dispatch rate, not step time (it reported >100%
    MFU).  At every window boundary we therefore force a device->host
    readback of ``value`` before reading the clock; steps *within* a window
    still pipeline freely, so the sync cost amortizes over ``log_steps``.
    """

    def __init__(self, batch_size, log_steps=20, step_flops=None,
                 num_devices=None, summary_writer=None):
        import jax

        self.batch_size = batch_size
        self.log_steps = log_steps
        self.step_flops = step_flops  # per-device FLOPs (post-partitioning)
        self.num_devices = num_devices or len(jax.devices())
        # optional tensorflowonspark_tpu.summary.SummaryWriter: window
        # scalars (loss/throughput/MFU) land in TensorBoard (chief-only by
        # caller convention)
        self.summary_writer = summary_writer
        self.global_steps = 0
        self.timestamp_log = []
        self.train_start_time = None
        self.start_time = None
        self.elapsed = 0.0
        # per-step loss vectors from K-step scan groups, buffered as DEVICE
        # arrays (reading them eagerly would sync every group and defeat
        # the async pipeline); drained into the summary writer at window
        # boundaries, where a sync happens anyway
        self._pending_losses = []
        self._loss_curve_end = 0  # last step the per-step curve has covered
        # Host copy of the value the last window boundary synced on (scalar
        # loss, or a per-step loss vector under K-steps-per-dispatch) — the
        # Trainer's training-health counters read it here, so observing the
        # loss costs no sync beyond the one the boundary already forced.
        self.last_synced_value = None

    def on_train_begin(self):
        self.train_start_time = time.time()
        self.start_time = time.time()
        self.timestamp_log.append((0, self.start_time))

    @staticmethod
    def _sync(value):
        """Force a device->host readback so the host clock reflects device
        completion; returns the host value (None when there was nothing to
        sync on).  A readback (not just ``block_until_ready``): on
        remotely-attached backends the transfer is the only barrier that
        provably spans the full dispatch chain."""
        if value is None:
            return None
        import jax

        return jax.device_get(jax.block_until_ready(value))

    def on_step_end(self, value=None):
        self.on_steps_end(1, value)

    def on_steps_end(self, n, value=None, window_value=None):
        """Record ``n`` global steps completed by one dispatch (n > 1 when a
        ``lax.scan`` group ran K steps on device, see ``Trainer.multi_step``).
        A window closes whenever the step counter crosses a ``log_steps``
        boundary; window length in steps is tracked exactly, so throughput
        stays honest even when boundaries land mid-group.

        ``value`` may be a length-``n`` PER-STEP loss vector (the scan's
        stacked ys): the TensorBoard loss curve then keeps full per-step
        density under K-steps-per-dispatch — points buffer as device arrays
        and flush at window boundaries, so no extra syncs enter the
        pipeline.

        ``window_value`` may carry an O(1) DEVICE SCALAR summarizing the
        dispatch (e.g. the scan-computed group loss mean): boundaries then
        sync on it instead of the K-element vector, so the per-boundary
        device->host readback stays O(1) no matter how large K grows.
        ``last_synced_value`` becomes that scalar (a mean, not the last
        step's loss — NaN/Inf still propagate through the mean, so
        nonfinite health detection keeps working)."""
        if self.train_start_time is None:
            self.on_train_begin()
        before = self.global_steps
        self.global_steps += n
        vec = value if getattr(value, "ndim", 0) else None
        if vec is not None and self.summary_writer is not None:
            self._pending_losses.append((before, vec))
        if self.global_steps // self.log_steps > before // self.log_steps:
            synced = self._sync(
                window_value if window_value is not None else value)
            if synced is not None:
                self.last_synced_value = synced
            now = time.time()
            window_steps = self.global_steps - self.timestamp_log[-1][0]
            elapsed = now - self.start_time
            eps = self.batch_size * window_steps / elapsed
            msg = ("step %d: %.1f examples/sec (%.1f/sec/chip), "
                   "%.1f ms/step" % (
                       self.global_steps, eps, eps / self.num_devices,
                       1000 * elapsed / window_steps))
            mfu = self.mfu(elapsed / window_steps)
            if mfu is not None:
                msg += ", %.1f%% MFU" % (100 * mfu)
            logger.info(msg)
            if self.summary_writer is not None:
                # drain buffered per-step loss vectors first (their steps
                # completed long ago: device_get here stalls nothing)
                flushed_loss = self._drain_pending_losses()
                scalars = {"examples_per_sec": eps,
                           "ms_per_step": 1000 * elapsed / window_steps}
                if mfu is not None:
                    scalars["mfu"] = mfu
                if value is not None and not flushed_loss:
                    try:
                        scalars["loss"] = float(value)
                    except TypeError:
                        pass  # non-scalar sync value: skip the loss curve
                self.summary_writer.add_scalars(scalars, self.global_steps)
                # flush per window (amortized by log_steps): live dashboards
                # update mid-run and a killed job keeps its curves
                self.summary_writer.flush()
            self.timestamp_log.append((self.global_steps, now))
            self.start_time = now

    def _drain_pending_losses(self):
        """Write buffered per-step loss vectors to the summary writer;
        returns True if any point was written."""
        import jax
        import numpy as np

        for s0, v in self._pending_losses:
            arr = np.asarray(jax.device_get(v))
            for i, l in enumerate(arr):
                self.summary_writer.add_scalars({"loss": float(l)}, s0 + i + 1)
            self._loss_curve_end = max(self._loss_curve_end, s0 + len(arr))
        drained = bool(self._pending_losses)
        self._pending_losses = []
        return drained

    def on_train_end(self, value=None):
        synced = self._sync(value)
        if synced is not None:
            self.last_synced_value = synced
        self.elapsed = time.time() - self.train_start_time
        if self.summary_writer is not None and self._pending_losses:
            # flush the tail of the per-step loss curve (steps since the
            # last window boundary)
            self._drain_pending_losses()
            self.summary_writer.flush()

    def mfu(self, step_seconds):
        # step_flops and peak are both per-device figures (XLA cost analysis
        # reports the partitioned per-device module), so no num_devices term;
        # delegated so the runtime accountant provably shares the formula.
        return mfu_from_step_time(self.step_flops, step_seconds)

    # -- summary (reference build_stats, common.py:202-245) ---------------

    def avg_examples_per_second(self):
        log = self.timestamp_log
        if len(log) >= 2:
            steps = log[-1][0] - log[0][0]
            elapsed = log[-1][1] - log[0][1]
            return self.batch_size * steps / elapsed if elapsed > 0 else 0.0
        if self.elapsed and self.global_steps:
            # run shorter than one log window: fall back to the (synced)
            # whole-run elapsed from on_train_end
            return self.batch_size * self.global_steps / self.elapsed
        return 0.0

    def build_stats(self, loss=None, eval_loss=None, accuracy=None):
        eps = self.avg_examples_per_second()
        stats = {
            "global_steps": self.global_steps,
            "avg_exp_per_second": eps,
            "exp_per_second_per_chip": eps / self.num_devices,
            "train_finish_time": time.time(),
            "elapsed_seconds": self.elapsed,
        }
        avg_step = (self.elapsed / self.global_steps
                    if self.global_steps and self.elapsed else None)
        if avg_step:
            stats["avg_step_seconds"] = avg_step
            mfu = self.mfu(avg_step)
            if mfu is not None:
                stats["mfu"] = mfu
        if loss is not None:
            stats["loss"] = float(loss)
        if eval_loss is not None:
            stats["eval_loss"] = float(eval_loss)
        if accuracy is not None:
            stats["accuracy_top_1"] = float(accuracy)
        return stats

    def log_stats(self, **kwargs):
        stats = self.build_stats(**kwargs)
        logger.info("train stats: %s", json.dumps(stats, default=float))
        if self.summary_writer is not None:
            keys = ["loss", "avg_exp_per_second", "avg_step_seconds",
                    "mfu", "eval_loss", "accuracy_top_1"]
            if self._loss_curve_end >= self.global_steps:
                keys.remove("loss")  # per-step curve already has this point
            final = {k: float(stats[k]) for k in keys if k in stats}
            self.summary_writer.add_scalars(final, self.global_steps)
            self.summary_writer.flush()
        return stats
