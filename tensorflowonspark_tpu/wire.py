"""Self-describing zero-copy columnar wire format for the shm-ring feed plane.

The ring's original fast path pickled each
:class:`~tensorflowonspark_tpu.marker.ColChunk` — PERF.md's stage profile
shows that pack+pickle (3.57 ms) plus unpickle (1.88 ms) per 1024-row batch
dwarf the raw ring round-trip (1.57 ms), and every payload byte was copied
twice more through intermediate pickle buffers.  This module replaces the
pickle bytes with a **frame**: a small self-describing header followed by
each column's raw buffer, so the producer gather-writes the columns
straight into the ring (``Ring.put_vectored`` — one memcpy per column) and
the consumer wraps the in-ring record with ``np.frombuffer`` views and
copies each column exactly once (``decode(copy=True)``), directly into
batch assembly.  tf.data (arXiv:2101.12127) and the tf.data service
(arXiv:2210.14826) both identify exactly this host-side input
serialization as the scaling wall once transport is fast.

Frame layout (all little-endian, no alignment padding)::

    fixed header (32 bytes):
      0:4    magic  b"TFWC"
      4:6    u16    version (1)
      6:8    u16    flags   (bit 0: tuple_rows)
      8:12   u32    ncols
      12:20  u64    count        (rows promised — the token desync check)
      20:28  u64    frame_len    (total frame bytes, header included)
      28:32  u32    header_len   (data section offset = end of descriptors)
    per-column descriptor (32 + 8*ndim bytes):
      8s     dtype.str, NUL-padded (e.g. b"<f4")
      u32    ndim
      u32    reserved (0)
      u64    offset   (column data start, from frame start)
      u64    nbytes
      u64*n  shape

Only plain numeric/bool/complex dtypes (``dtype.kind in "biufc"``) on
C-contiguous arrays are framable; anything else (object columns, unicode,
non-contiguous views, ragged data) returns ``None`` from :func:`encode`
and the caller falls back to the pickled transport — the same soft-fallback
contract :func:`~tensorflowonspark_tpu.columnar.rows_to_fields` uses.
"""

import math
import os
import struct

import numpy as np

__all__ = [
    "FrameError", "WIRE_PICKLE", "WIRE_COLV1", "enabled",
    "encode", "encode_chunk", "frame_bytes", "frame_chunk_bytes", "decode",
    "decode_chunk",
]

MAGIC = b"TFWC"
VERSION = 1

# Wire-format tags carried by marker.ShmChunk tokens (and reported by
# DataFeed.wire_formats / the bench feedplane leg):
WIRE_PICKLE = "pickle"   # pickled Chunk/ColChunk object bytes (legacy path)
WIRE_COLV1 = "colv1"     # this module's columnar frame, version 1

_FIXED = struct.Struct("<4sHHIQQI")     # magic ver flags ncols count flen hlen
_DESC = struct.Struct("<8sIIQQ")        # dtype ndim reserved offset nbytes

_FRAMABLE_KINDS = "biufc"   # bool, (u)int, float, complex — raw-copy safe


class FrameError(ValueError):
    """A buffer is not a valid columnar frame (truncated, corrupt, or an
    unsupported version) — the consumer must not trust any of its fields."""


def enabled():
    """Whether the framed path may be used (``TFOS_WIRE_FORMAT=pickle``
    forces the pickled transport — the A/B knob for profiling and parity
    testing)."""
    return os.environ.get("TFOS_WIRE_FORMAT", "").lower() != WIRE_PICKLE


def encode(columns, count, tuple_rows):
    """Frame ``columns`` for a gather write.

    Returns ``[header_bytes, col0, col1, ...]`` — the header plus the column
    ndarrays themselves, ready for ``Ring.put_vectored`` (no column bytes
    are copied here) — or ``None`` when the columns aren't framable
    (non-ndarray, non-numeric dtype, or non-contiguous: callers fall back
    to pickle).
    """
    descs = []
    header_len = _FIXED.size + sum(
        _DESC.size + 8 * getattr(c, "ndim", 0) for c in columns)
    offset = header_len
    for col in columns:
        if not isinstance(col, np.ndarray):
            return None
        if col.dtype.kind not in _FRAMABLE_KINDS:
            return None
        if not col.flags.c_contiguous:
            return None
        dstr = col.dtype.str.encode("ascii")
        if len(dstr) > 8:
            return None
        descs.append(_DESC.pack(dstr, col.ndim, 0, offset, col.nbytes)
                     + struct.pack("<%dQ" % col.ndim, *col.shape))
        offset += col.nbytes
    header = _FIXED.pack(MAGIC, VERSION, 1 if tuple_rows else 0,
                         len(columns), count, offset, header_len)
    return [header + b"".join(descs)] + list(columns)


def encode_chunk(chunk):
    """Frame a :class:`~tensorflowonspark_tpu.marker.ColChunk` (or ``None``
    when it isn't framable)."""
    return encode(chunk.columns, chunk.count, chunk.tuple_rows)


def frame_bytes(columns, count, tuple_rows):
    """One contiguous frame as bytes (tests / non-vectored transports); the
    ring path uses :func:`encode`'s gather parts instead to skip this join.
    ``None`` when not framable."""
    parts = encode(columns, count, tuple_rows)
    if parts is None:
        return None
    return b"".join(p.tobytes() if isinstance(p, np.ndarray) else p
                    for p in parts)


def frame_chunk_bytes(chunk):
    """One contiguous frame for a
    :class:`~tensorflowonspark_tpu.marker.ColChunk` (``None`` when not
    framable) — the byte-stream transports' convenience (TCP data service);
    the ring path uses :func:`encode_chunk`'s gather parts."""
    return frame_bytes(chunk.columns, chunk.count, chunk.tuple_rows)


def decode(buf, copy=True):
    """Parse one frame; returns ``(columns, count, tuple_rows)``.

    ``copy=True`` (the ring path's contract): each column is copied exactly
    once out of ``buf`` — required when ``buf`` is in-ring memory that the
    producer reclaims after ``Ring.consume``.  ``copy=False`` returns
    zero-copy ``np.frombuffer`` views into ``buf`` (only safe while the
    caller keeps ``buf`` alive and unrecycled).

    Raises :class:`FrameError` on anything malformed: wrong magic/version,
    truncation, descriptor/shape inconsistencies, out-of-bounds column
    extents.
    """
    mv = memoryview(buf)
    if mv.ndim != 1 or mv.itemsize != 1:
        mv = mv.cast("B")
    total = len(mv)
    if total < _FIXED.size:
        raise FrameError("frame shorter than fixed header "
                         "({} < {} bytes)".format(total, _FIXED.size))
    magic, version, flags, ncols, count, frame_len, header_len = \
        _FIXED.unpack_from(mv, 0)
    if magic != MAGIC:
        raise FrameError("bad frame magic {!r}".format(bytes(magic)))
    if version != VERSION:
        raise FrameError("unsupported frame version {}".format(version))
    if frame_len != total:
        raise FrameError("frame length mismatch: header says {} bytes, "
                         "buffer has {}".format(frame_len, total))
    if not _FIXED.size <= header_len <= total:
        raise FrameError("header_len {} out of range".format(header_len))
    columns = []
    off = _FIXED.size
    for c in range(ncols):
        if off + _DESC.size > header_len:
            raise FrameError("descriptor {} overruns header".format(c))
        dstr, ndim, _reserved, offset, nbytes = _DESC.unpack_from(mv, off)
        off += _DESC.size
        if off + 8 * ndim > header_len:
            raise FrameError("shape of column {} overruns header".format(c))
        shape = struct.unpack_from("<%dQ" % ndim, mv, off)
        off += 8 * ndim
        try:
            dtype = np.dtype(dstr.rstrip(b"\0").decode("ascii"))
        except (TypeError, UnicodeDecodeError) as e:
            raise FrameError("column {} has unparseable dtype: {}".format(c, e))
        if dtype.kind not in _FRAMABLE_KINDS:
            raise FrameError("column {} has non-framable dtype {}".format(
                c, dtype))
        n_elem = math.prod(shape)
        if nbytes != n_elem * dtype.itemsize:
            raise FrameError(
                "column {} nbytes {} != shape {} x itemsize {}".format(
                    c, nbytes, shape, dtype.itemsize))
        if offset < header_len or offset + nbytes > total:
            raise FrameError("column {} extent [{}, {}) outside frame of "
                             "{} bytes".format(c, offset, offset + nbytes,
                                               total))
        arr = np.frombuffer(mv, dtype=dtype, count=n_elem,
                            offset=offset).reshape(shape)
        columns.append(arr.copy() if copy else arr)
    return tuple(columns), count, bool(flags & 1)


def decode_chunk(buf, copy=True):
    """Parse one frame into a :class:`~tensorflowonspark_tpu.marker.ColChunk`."""
    from tensorflowonspark_tpu import marker

    columns, count, tuple_rows = decode(buf, copy=copy)
    return marker.ColChunk(columns, count, tuple_rows)
