"""Self-describing zero-copy columnar wire format for the shm-ring feed plane.

The ring's original fast path pickled each
:class:`~tensorflowonspark_tpu.marker.ColChunk` — PERF.md's stage profile
shows that pack+pickle (3.57 ms) plus unpickle (1.88 ms) per 1024-row batch
dwarf the raw ring round-trip (1.57 ms), and every payload byte was copied
twice more through intermediate pickle buffers.  This module replaces the
pickle bytes with a **frame**: a small self-describing header followed by
each column's raw buffer, so the producer gather-writes the columns
straight into the ring (``Ring.put_vectored`` — one memcpy per column) and
the consumer wraps the in-ring record with ``np.frombuffer`` views and
copies each column exactly once (``decode(copy=True)``), directly into
batch assembly.  tf.data (arXiv:2101.12127) and the tf.data service
(arXiv:2210.14826) both identify exactly this host-side input
serialization as the scaling wall once transport is fast.

Frame layout (all little-endian, no alignment padding)::

    fixed header (32 bytes):
      0:4    magic  b"TFWC"
      4:6    u16    version (1)
      6:8    u16    flags   (bit 0: tuple_rows)
      8:12   u32    ncols
      12:20  u64    count        (rows promised — the token desync check)
      20:28  u64    frame_len    (total frame bytes, header included)
      28:32  u32    header_len   (data section offset = end of descriptors)
    per-column descriptor (32 + 8*ndim bytes):
      8s     dtype.str, NUL-padded (e.g. b"<f4")
      u32    ndim
      u32    reserved (0)
      u64    offset   (column data start, from frame start)
      u64    nbytes
      u64*n  shape

Only plain numeric/bool/complex dtypes (``dtype.kind in "biufc"``) on
C-contiguous arrays are framable; anything else (object columns, unicode,
non-contiguous views, ragged data) returns ``None`` from :func:`encode`
and the caller falls back to the pickled transport — the same soft-fallback
contract :func:`~tensorflowonspark_tpu.columnar.rows_to_fields` uses.

**Per-column wire compression** (byte-stream transports only — the shm
ring gather-writes raw column buffers and never compresses): the
per-column descriptor's reserved word carries a codec tag (0 = raw).  A
tagged column's ``nbytes`` is its on-wire (compressed) size; the true
size is recomputed from shape × itemsize and validated after
decompression.  Codecs are stdlib ``zlib`` (``"zlib"`` /
``"zlib-<level>"``) plus ``lz4`` when the optional ``lz4`` package is
importable; which codec a producer may use is negotiated out-of-band at
stream dial (see :meth:`dataservice.ServiceFeed`), riding the same
format-tag convention as the pickle fallback, and each column is
compressed only when a sampled ratio check says it pays — incompressible
columns stay raw inside an otherwise-compressed frame.
"""

import math
import os
import struct
import zlib

import numpy as np

try:  # optional codec — never a hard dependency (bare containers lack it)
    import lz4.frame as _lz4
except Exception:  # pragma: no cover - import-environment dependent
    _lz4 = None

__all__ = [
    "FrameError", "WIRE_PICKLE", "WIRE_COLV1", "enabled",
    "encode", "encode_chunk", "frame_bytes", "frame_chunk_bytes", "decode",
    "decode_chunk", "supported_codecs", "codec_supported", "negotiate_codec",
]

MAGIC = b"TFWC"
VERSION = 1

# Wire-format tags carried by marker.ShmChunk tokens (and reported by
# DataFeed.wire_formats / the bench feedplane leg):
WIRE_PICKLE = "pickle"   # pickled Chunk/ColChunk object bytes (legacy path)
WIRE_COLV1 = "colv1"     # this module's columnar frame, version 1

_FIXED = struct.Struct("<4sHHIQQI")     # magic ver flags ncols count flen hlen
_DESC = struct.Struct("<8sIIQQ")        # dtype ndim codec offset nbytes

_FRAMABLE_KINDS = "biufc"   # bool, (u)int, float, complex — raw-copy safe

# Frame flags (fixed-header u16)
FLAG_TUPLE_ROWS = 0x1
FLAG_COMPRESSED = 0x2   # at least one column carries a codec tag

# Per-column codec tags (the descriptor word that was reserved=0 in the
# original frame layout, so raw frames are bit-identical to version 1
# frames from before compression existed)
_CODEC_RAW = 0
_CODEC_ZLIB = 1
_CODEC_LZ4 = 2
_CODEC_NAMES = {_CODEC_ZLIB: "zlib", _CODEC_LZ4: "lz4"}

_ZLIB_DEFAULT_LEVEL = 1   # speed-dominant: wire compression rides hot paths

# Pay-off sampling: compress at most _SAMPLE_MAX leading bytes of a column
# first; only if the sample shrinks below _PAY_RATIO is the full column
# compressed (and even then the full result must actually be smaller).
# Columns under _MIN_COL_BYTES never pay for the codec framing overhead.
_SAMPLE_MAX = 1 << 16
_PAY_RATIO = 0.9
_MIN_COL_BYTES = 512


class FrameError(ValueError):
    """A buffer is not a valid columnar frame (truncated, corrupt, or an
    unsupported version) — the consumer must not trust any of its fields."""


def enabled():
    """Whether the framed path may be used (``TFOS_WIRE_FORMAT=pickle``
    forces the pickled transport — the A/B knob for profiling and parity
    testing)."""
    return os.environ.get("TFOS_WIRE_FORMAT", "").lower() != WIRE_PICKLE


def _parse_codec(name):
    """``(tag, level)`` for a codec name; raises ``ValueError`` on a name
    this host cannot encode (unknown, or ``lz4`` without the package)."""
    if name is None or name == "none":
        return _CODEC_RAW, None
    if name == "zlib":
        return _CODEC_ZLIB, _ZLIB_DEFAULT_LEVEL
    if name.startswith("zlib-"):
        try:
            level = int(name[5:])
        except ValueError:
            raise ValueError("bad zlib level in codec {!r}".format(name))
        if not 0 <= level <= 9:
            raise ValueError("zlib level out of range in codec "
                             "{!r}".format(name))
        return _CODEC_ZLIB, level
    if name == "lz4":
        if _lz4 is None:
            raise ValueError("codec lz4 requested but the lz4 package is "
                             "not importable on this host")
        return _CODEC_LZ4, None
    raise ValueError("unknown wire codec {!r}".format(name))


def codec_supported(name):
    """Whether this host can encode AND decode codec ``name``."""
    try:
        _parse_codec(name)
    except ValueError:
        return False
    return True


def supported_codecs():
    """Codec names this host supports, in preference order (fastest
    first); always ends with ``"none"`` so negotiation can land on raw."""
    names = ["lz4"] if _lz4 is not None else []
    names += ["zlib", "none"]
    return names


def negotiate_codec(offered):
    """First codec in ``offered`` (the consumer's dial hello, its
    preference order) that this host supports, or ``None`` — the
    producer-side half of the dial negotiation."""
    for name in offered or ():
        if name != "none" and codec_supported(name):
            return name
    return None


def _compress(tag, level, data):
    if tag == _CODEC_ZLIB:
        return zlib.compress(bytes(data), level)
    if tag == _CODEC_LZ4:
        return _lz4.compress(bytes(data))
    raise ValueError("cannot compress with codec tag {}".format(tag))


def _decompress(tag, col_idx, data):
    """Raw bytes of a tagged column; :class:`FrameError` NAMES the codec
    (or its unknown tag) so a mixed-version fleet diagnoses itself."""
    name = _CODEC_NAMES.get(tag)
    if name is None:
        raise FrameError("column {} compressed with unknown codec tag {}"
                         .format(col_idx, tag))
    try:
        if tag == _CODEC_ZLIB:
            return zlib.decompress(bytes(data))
        if _lz4 is None:
            raise FrameError(
                "column {} compressed with codec {}, which is not "
                "available on this host".format(col_idx, name))
        return _lz4.decompress(bytes(data))
    except FrameError:
        raise
    except Exception as e:
        raise FrameError("column {} failed to decompress with codec {}: "
                         "{}".format(col_idx, name, e))


def encode(columns, count, tuple_rows):
    """Frame ``columns`` for a gather write.

    Returns ``[header_bytes, col0, col1, ...]`` — the header plus the column
    ndarrays themselves, ready for ``Ring.put_vectored`` (no column bytes
    are copied here) — or ``None`` when the columns aren't framable
    (non-ndarray, non-numeric dtype, or non-contiguous: callers fall back
    to pickle).
    """
    descs = []
    header_len = _FIXED.size + sum(
        _DESC.size + 8 * getattr(c, "ndim", 0) for c in columns)
    offset = header_len
    for col in columns:
        if not isinstance(col, np.ndarray):
            return None
        if col.dtype.kind not in _FRAMABLE_KINDS:
            return None
        if not col.flags.c_contiguous:
            return None
        dstr = col.dtype.str.encode("ascii")
        if len(dstr) > 8:
            return None
        descs.append(_DESC.pack(dstr, col.ndim, 0, offset, col.nbytes)
                     + struct.pack("<%dQ" % col.ndim, *col.shape))
        offset += col.nbytes
    header = _FIXED.pack(MAGIC, VERSION, 1 if tuple_rows else 0,
                         len(columns), count, offset, header_len)
    return [header + b"".join(descs)] + list(columns)


def encode_chunk(chunk):
    """Frame a :class:`~tensorflowonspark_tpu.marker.ColChunk` (or ``None``
    when it isn't framable)."""
    return encode(chunk.columns, chunk.count, chunk.tuple_rows)


def _column_wire_form(col, tag, level):
    """``(codec_tag, wire_bytes)`` for one column: the compressed bytes
    when the sampled ratio check says the codec pays, else the raw buffer
    (tag 0).  ``col`` is already framability-checked and C-contiguous."""
    if tag == _CODEC_RAW or col.nbytes < _MIN_COL_BYTES:
        return _CODEC_RAW, col
    data = memoryview(col).cast("B")
    if col.nbytes > _SAMPLE_MAX:
        sample = _compress(tag, level, data[:_SAMPLE_MAX])
        if len(sample) > _PAY_RATIO * _SAMPLE_MAX:
            return _CODEC_RAW, col
    comp = _compress(tag, level, data)
    if len(comp) >= _PAY_RATIO * col.nbytes:
        return _CODEC_RAW, col
    return tag, comp


def frame_bytes(columns, count, tuple_rows, codec=None, stats=None):
    """One contiguous frame as bytes (byte-stream transports / tests); the
    ring path uses :func:`encode`'s gather parts instead to skip this join.
    ``None`` when not framable.

    ``codec`` (a :func:`supported_codecs` name) enables per-column wire
    compression: each column is tagged and compressed only when the
    sampled ratio check says it pays.  ``stats``, when a dict, is
    incremented in place with ``raw_bytes`` / ``wire_bytes`` /
    ``cols_compressed`` / ``cols_raw`` / ``frames`` — the producer-side
    compression accounting (``raw_bytes`` is what the frame would have
    cost uncompressed).
    """
    tag, level = _parse_codec(codec)
    if tag == _CODEC_RAW:
        parts = encode(columns, count, tuple_rows)
        if parts is None:
            return None
        out = b"".join(p.tobytes() if isinstance(p, np.ndarray) else p
                       for p in parts)
        if stats is not None:
            stats["frames"] = stats.get("frames", 0) + 1
            stats["raw_bytes"] = stats.get("raw_bytes", 0) + len(out)
            stats["wire_bytes"] = stats.get("wire_bytes", 0) + len(out)
            stats["cols_raw"] = stats.get("cols_raw", 0) + len(columns)
        return out
    header_len = _FIXED.size + sum(
        _DESC.size + 8 * getattr(c, "ndim", 0) for c in columns)
    descs, bodies = [], []
    offset = header_len
    raw_total = header_len
    compressed = 0
    for col in columns:
        if (not isinstance(col, np.ndarray)
                or col.dtype.kind not in _FRAMABLE_KINDS
                or not col.flags.c_contiguous):
            return None
        dstr = col.dtype.str.encode("ascii")
        if len(dstr) > 8:
            return None
        ctag, body = _column_wire_form(col, tag, level)
        nbytes = body.nbytes if isinstance(body, np.ndarray) else len(body)
        descs.append(_DESC.pack(dstr, col.ndim, ctag, offset, nbytes)
                     + struct.pack("<%dQ" % col.ndim, *col.shape))
        bodies.append(body)
        offset += nbytes
        raw_total += col.nbytes
        compressed += ctag != _CODEC_RAW
    flags = (FLAG_TUPLE_ROWS if tuple_rows else 0) | (
        FLAG_COMPRESSED if compressed else 0)
    header = _FIXED.pack(MAGIC, VERSION, flags, len(columns), count,
                         offset, header_len)
    out = b"".join([header] + descs
                   + [b.tobytes() if isinstance(b, np.ndarray) else b
                      for b in bodies])
    if stats is not None:
        stats["frames"] = stats.get("frames", 0) + 1
        stats["raw_bytes"] = stats.get("raw_bytes", 0) + raw_total
        stats["wire_bytes"] = stats.get("wire_bytes", 0) + len(out)
        stats["cols_compressed"] = stats.get("cols_compressed", 0) + compressed
        stats["cols_raw"] = (stats.get("cols_raw", 0)
                             + len(columns) - compressed)
    return out


def frame_chunk_bytes(chunk, codec=None, stats=None):
    """One contiguous frame for a
    :class:`~tensorflowonspark_tpu.marker.ColChunk` (``None`` when not
    framable) — the byte-stream transports' convenience (TCP data service);
    the ring path uses :func:`encode_chunk`'s gather parts.  ``codec`` /
    ``stats`` as :func:`frame_bytes`."""
    return frame_bytes(chunk.columns, chunk.count, chunk.tuple_rows,
                       codec=codec, stats=stats)


def decode(buf, copy=True, info=None):
    """Parse one frame; returns ``(columns, count, tuple_rows)``.

    ``copy=True`` (the ring path's contract): each column is copied exactly
    once out of ``buf`` — required when ``buf`` is in-ring memory that the
    producer reclaims after ``Ring.consume``.  ``copy=False`` returns
    zero-copy ``np.frombuffer`` views into ``buf`` (only safe while the
    caller keeps ``buf`` alive and unrecycled).  Compressed columns are
    always materialized from their freshly decompressed buffer, never as
    views into ``buf``.

    ``info``, when a dict, receives decode-side compression accounting:
    ``codecs`` (sorted names of codecs seen in this frame, empty when
    raw), ``raw_bytes`` (the frame's size had it been uncompressed), and
    ``cols_compressed``.

    Raises :class:`FrameError` on anything malformed: wrong magic/version,
    truncation, descriptor/shape inconsistencies, out-of-bounds column
    extents, an unknown or locally unavailable codec tag, or compressed
    data that does not decompress to the descriptor's shape.
    """
    mv = memoryview(buf)
    if mv.ndim != 1 or mv.itemsize != 1:
        mv = mv.cast("B")
    total = len(mv)
    if total < _FIXED.size:
        raise FrameError("frame shorter than fixed header "
                         "({} < {} bytes)".format(total, _FIXED.size))
    magic, version, flags, ncols, count, frame_len, header_len = \
        _FIXED.unpack_from(mv, 0)
    if magic != MAGIC:
        raise FrameError("bad frame magic {!r}".format(bytes(magic)))
    if version != VERSION:
        raise FrameError("unsupported frame version {}".format(version))
    if frame_len != total:
        raise FrameError("frame length mismatch: header says {} bytes, "
                         "buffer has {}".format(frame_len, total))
    if not _FIXED.size <= header_len <= total:
        raise FrameError("header_len {} out of range".format(header_len))
    columns = []
    codecs_seen = set()
    raw_total = header_len
    n_compressed = 0
    off = _FIXED.size
    for c in range(ncols):
        if off + _DESC.size > header_len:
            raise FrameError("descriptor {} overruns header".format(c))
        dstr, ndim, codec_tag, offset, nbytes = _DESC.unpack_from(mv, off)
        off += _DESC.size
        if off + 8 * ndim > header_len:
            raise FrameError("shape of column {} overruns header".format(c))
        shape = struct.unpack_from("<%dQ" % ndim, mv, off)
        off += 8 * ndim
        try:
            dtype = np.dtype(dstr.rstrip(b"\0").decode("ascii"))
        except (TypeError, UnicodeDecodeError) as e:
            raise FrameError("column {} has unparseable dtype: {}".format(c, e))
        if dtype.kind not in _FRAMABLE_KINDS:
            raise FrameError("column {} has non-framable dtype {}".format(
                c, dtype))
        n_elem = math.prod(shape)
        raw_nbytes = n_elem * dtype.itemsize
        raw_total += raw_nbytes
        if codec_tag == _CODEC_RAW and nbytes != raw_nbytes:
            raise FrameError(
                "column {} nbytes {} != shape {} x itemsize {}".format(
                    c, nbytes, shape, dtype.itemsize))
        if offset < header_len or offset + nbytes > total:
            raise FrameError("column {} extent [{}, {}) outside frame of "
                             "{} bytes".format(c, offset, offset + nbytes,
                                               total))
        if codec_tag == _CODEC_RAW:
            arr = np.frombuffer(mv, dtype=dtype, count=n_elem,
                                offset=offset).reshape(shape)
            columns.append(arr.copy() if copy else arr)
        else:
            raw = _decompress(codec_tag, c, mv[offset:offset + nbytes])
            if len(raw) != raw_nbytes:
                raise FrameError(
                    "column {} decompressed to {} bytes, expected shape {} "
                    "x itemsize {} = {}".format(c, len(raw), shape,
                                                dtype.itemsize, raw_nbytes))
            # the decompressed buffer is private to this column: a view of
            # it is already safe under both copy contracts
            columns.append(np.frombuffer(raw, dtype=dtype,
                                         count=n_elem).reshape(shape))
            codecs_seen.add(_CODEC_NAMES[codec_tag])
            n_compressed += 1
    if info is not None:
        info["codecs"] = sorted(codecs_seen)
        info["raw_bytes"] = raw_total
        info["cols_compressed"] = n_compressed
    return tuple(columns), count, bool(flags & FLAG_TUPLE_ROWS)


def decode_chunk(buf, copy=True, info=None):
    """Parse one frame into a :class:`~tensorflowonspark_tpu.marker.ColChunk`.
    ``info`` as :func:`decode`."""
    from tensorflowonspark_tpu import marker

    columns, count, tuple_rows = decode(buf, copy=copy, info=info)
    return marker.ColChunk(columns, count, tuple_rows)
