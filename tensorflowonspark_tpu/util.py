"""Miscellaneous host-side utilities (reference ``util.py:19-75``).

These run in driver and executor processes alike and must not import jax (the
driver never initializes a TPU; executor processes import jax lazily inside the
node runtime, mirroring the reference's deferred ``import tensorflow`` at
``TFSparkNode.py:137``).
"""

import errno
import logging
import os
import socket

logger = logging.getLogger(__name__)

# Name of the CWD file that persists this executor's id so that later feed tasks
# scheduled onto the same executor can locate its manager (reference
# ``util.py:66-75`` and the executor-id handshake described in SURVEY §7.4.2).
EXECUTOR_ID_FILE = "executor_id"


def get_ip_address():
    """Best-effort IP address of the current host.

    Uses the UDP-connect trick (no packets are sent) like reference
    ``util.py:41-54``; falls back to loopback when the host is offline.
    """
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
    except OSError:
        ip = "127.0.0.1"
    finally:
        s.close()
    return ip


def find_in_path(path, file_name):
    """Find a file in a ':'-separated path string (reference ``util.py:57-63``)."""
    for p in path.split(os.pathsep):
        candidate = os.path.join(p, file_name)
        if os.path.exists(candidate) and os.path.isfile(candidate):
            return candidate
    return False


def write_executor_id(num, working_dir=None):
    """Persist this executor's id to a file in its working dir.

    Reference ``util.py:66-69``.  Later jobs (feed tasks) that land on the same
    executor read this file to reconnect to the long-running node's manager.
    """
    path = os.path.join(working_dir or os.getcwd(), EXECUTOR_ID_FILE)
    with open(path, "w") as f:
        f.write(str(num))


def read_executor_id(working_dir=None):
    """Read the executor id persisted by :func:`write_executor_id`.

    Reference ``util.py:72-75``.  Raises a descriptive error when the file is
    missing (a feed task arrived on an executor that never ran a node task —
    the one-task-per-executor discipline was violated).
    """
    path = os.path.join(working_dir or os.getcwd(), EXECUTOR_ID_FILE)
    try:
        with open(path) as f:
            return int(f.read())
    except OSError as e:
        if e.errno == errno.ENOENT:
            raise RuntimeError(
                "No executor_id file found in {!r}. A data-feeding task was "
                "scheduled on an executor that is not running a cluster node; "
                "ensure one task slot per executor (see cluster.run docs).".format(
                    os.path.dirname(path) or os.getcwd()
                )
            )
        raise


def single_node_env(num_tpu_chips=None):
    """Configure environment for a standalone single-node execution context.

    Reference ``util.py:19-38`` set up Hadoop classpath + CUDA_VISIBLE_DEVICES;
    the TPU-native equivalent constrains JAX's platform/visible-device view for
    per-executor model-parallel-free inference (pipeline transform path).
    """
    if num_tpu_chips is not None and num_tpu_chips == 0:
        # Force CPU execution (e.g. lightweight inference on non-TPU hosts).
        os.environ["JAX_PLATFORMS"] = "cpu"
