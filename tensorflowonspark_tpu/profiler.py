"""Profiling lifecycle (reference SURVEY §5.1).

The reference's tracing story is TensorBoard managed by the framework
(launch on the chief, URL via the cluster, kill at shutdown — implemented in
:mod:`~tensorflowonspark_tpu.node`) plus example-level step profiling
(``--profile_steps`` building a Keras profiler callback, reference
``examples/resnet/common.py:192-197,293-300``).  The TPU-native equivalents:

- :func:`start_server` — a per-host ``jax.profiler`` server so TensorBoard's
  profile plugin (or ``xprof``) can capture device traces on demand; the
  node runtime starts one per JAX-hosting node when ``cluster.run(...,
  profiler=True)`` and publishes the port in the cluster roster.
- :class:`StepProfiler` — programmatic trace capture over a step range,
  the ``--profile_steps start,stop`` behavior: call :meth:`on_step_end`
  once per step and the trace for [start, stop] lands in ``log_dir``.
"""

import logging

logger = logging.getLogger(__name__)


def start_server(port=None):
    """Start this process's jax.profiler gRPC server; returns the port
    (0 when jax lacks profiler support).  Idempotent per process — jax
    allows one server; subsequent calls return the first port.

    A FAILED start does not latch: ``_server_port`` stays ``None`` so the
    next call retries (a transient bind race / grpc hiccup at bring-up must
    not permanently cost the node its capture capability), while
    ``_server_state`` records the last outcome for the heartbeat counter
    (:func:`server_counters`)."""
    global _server_port, _server_state
    if _server_port is not None:
        return _server_port
    import jax

    if port is None:
        import socket

        sock = socket.socket()
        sock.bind(("", 0))
        port = sock.getsockname()[1]
        sock.close()
    try:
        jax.profiler.start_server(port)
    except Exception:
        logger.warning("jax profiler server unavailable", exc_info=True)
        _server_state = "down"
        return 0
    _server_port = port
    _server_state = "up"
    logger.info("jax profiler server listening on port %d", port)
    return port


def server_counters():
    """Heartbeat-counter view of the profiler server: ``{}`` when a start
    was never attempted, else ``profiler_server_up_max`` 1/0 (``_max``
    suffix -> rendered as a Prometheus gauge by the observatory)."""
    if _server_state is None:
        return {}
    return {"profiler_server_up_max": 1 if _server_state == "up" else 0}


_server_port = None
_server_state = None  # None = never attempted, else "up"/"down" (last try)


def parse_profile_steps(spec):
    """``"start,stop"`` -> (start, stop) step numbers (reference flag format,
    ``common.py:293-300``)."""
    if not spec:
        return None
    parts = [p.strip() for p in str(spec).split(",")]
    if len(parts) != 2:
        raise ValueError(
            "profile_steps must be 'start,stop', got {!r}".format(spec))
    start, stop = int(parts[0]), int(parts[1])
    if start < 0 or stop < start:
        raise ValueError(
            "need 0 <= start <= stop in profile_steps, got {!r}".format(spec))
    return start, stop


class StepProfiler(object):
    """Capture a device trace over a global-step range.

    Usage: ``prof = StepProfiler(log_dir, "10,20")`` then call
    ``prof.on_step_begin()`` before and ``prof.on_step_end()`` after every
    step; the trace starts before step ``start`` executes and stops after
    step ``stop``.  Callers that only hook ``on_step_end`` still get a
    trace (it starts lazily, one step late — after step ``start``
    completes) as long as the range spans more than one step.
    """

    def __init__(self, log_dir, profile_steps):
        self.log_dir = log_dir
        self.bounds = parse_profile_steps(profile_steps)
        self.step = 0
        self._active = False

    def _start(self):
        import jax

        jax.profiler.start_trace(self.log_dir)
        self._active = True
        logger.info("profiler trace started at step %d -> %s",
                    self.step, self.log_dir)

    def on_step_begin(self):
        if self.bounds and not self._active and self.step == self.bounds[0]:
            self._start()

    def on_step_end(self):
        self.step += 1
        if not self.bounds:
            return
        if self._active and self.step > self.bounds[1]:
            self.stop()
        elif (not self._active
              and self.bounds[0] <= self.step <= self.bounds[1]):
            # on_step_begin was never called: start late rather than never.
            self._start()

    def stop(self):
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False
            logger.info("profiler trace stopped at step %d", self.step)

    # Context-manager form: an exception between start/stop would otherwise
    # leak an active jax.profiler trace and poison the next capture attempt
    # (start_trace raises if one is already running).
    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()
        return False
