"""Cluster execution backends: who runs the long-lived per-host tasks.

The reference framework is welded to Apache Spark: the driver runs "jobs" whose
tasks are scheduled one-per-executor, executors are long-lived OS processes
that persist across jobs, and data reaches tasks as partition iterators
(reference ``TFCluster.py:312-329``, ``TFSparkNode.py:121-135``).  This module
abstracts exactly that contract so the TPU framework can run on:

- :class:`SparkBackend`  — a thin adapter over a live ``SparkContext`` (used
  when ``pyspark`` is installed; API-compatible with the reference deployment).
- :class:`LocalBackend`  — a built-in multi-process standalone cluster: N
  long-lived executor processes on this host, a driver-side scheduler that
  dispatches one task per free executor, per-executor working directories, and
  partition-iterator task semantics.  This is the moral equivalent of the
  reference's test rig (a local Spark Standalone cluster with separate worker
  processes, ``test/run_tests.sh:15-22``, ``test/README.md:10``) promoted to a
  first-class deployment mode — one process per TPU host is the natural
  granularity for JAX/libtpu anyway (SURVEY §7.2).

The backend contract (used by :mod:`~tensorflowonspark_tpu.cluster`):

- ``foreach_partition_async(partitions, fn) -> JobHandle`` — run ``fn(iter)``
  once per partition on some executor; non-blocking ("start job" / "feed job").
- ``map_partitions(partitions, fn) -> list`` — run ``fn(iter)`` per partition,
  collect per-partition result lists (inference results job).
- one task slot per executor: a task occupies its executor until it returns,
  which is what lets the framework co-locate feed tasks with the long-running
  node process via the executor-id working-dir handshake (``util.py:66-75``).
"""

import logging
import os
import queue as _queue
import shutil
import tempfile
import threading
import time
import traceback
import weakref

import cloudpickle
from multiprocessing import get_context
from multiprocessing import util as _mp_util

logger = logging.getLogger(__name__)

#: LocalBackends that have not been stop()ped.  A leaked backend would hang
#: interpreter shutdown: multiprocessing's exit hook joins non-daemon
#: children, and an idle executor blocks on its command pipe forever (the
#: executors can't be daemonic — their tasks fork manager-server children).
#: A plain ``atexit`` handler can't help: multiprocessing registers its own
#: lazily at the first spawn, so LIFO ordering would run the join loop
#: first.  ``util.Finalize`` with an exitpriority runs INSIDE that hook,
#: before the join loop, so leaked executors are stopped in time.
_live_backends = weakref.WeakSet()


def _reap_leaked_backends():
    for backend in list(_live_backends):
        if not backend._stopped:
            logger.warning(
                "LocalBackend leaked (never stopped); stopping at exit")
            try:
                backend.stop()
            except Exception:
                pass


_mp_util.Finalize(None, _reap_leaked_backends, exitpriority=100)


def partition(data, num_partitions):
    """Split a list into ``num_partitions`` contiguous partitions.

    The local-mode stand-in for ``sc.parallelize(data, n)``; Spark's formula
    (elements spread as evenly as possible) is used so partition sizes match
    what the reference's feeders would see.
    """
    items = list(data)
    n = len(items)
    out = []
    for i in range(num_partitions):
        start = (i * n) // num_partitions
        stop = ((i + 1) * n) // num_partitions
        out.append(items[start:stop])
    return out


class JobHandle(object):
    """Handle for an asynchronously running backend job."""

    def __init__(self, num_tasks):
        self.num_tasks = num_tasks
        self._done = threading.Event()
        self._lock = threading.Lock()
        self._completed = 0
        self.error = None  # first task error (formatted traceback string)
        self.results = [None] * num_tasks
        self.task_errors = [None] * num_tasks  # per-task error strings

    def _task_done(self, index, ok, payload):
        with self._lock:
            if ok:
                self.results[index] = payload
                self.task_errors[index] = None
            else:
                self.task_errors[index] = payload
                if self.error is None:
                    self.error = payload
            self._completed += 1
            if self._completed >= self.num_tasks or not ok:
                self._done.set()

    def _set_progress(self, completed):
        """Monotonically update the completed-task count from an external
        progress source (Spark statusTracker) without firing completion —
        task results/errors still arrive via ``_task_done``."""
        with self._lock:
            if completed > self._completed:
                self._completed = min(completed, self.num_tasks)

    def _finish_ok(self):
        """Mark the whole job successfully finished (backends that only
        observe job-level completion, e.g. Spark's ``foreachPartition``)."""
        with self._lock:
            self._completed = self.num_tasks
            self._done.set()

    def done(self):
        return self._done.is_set()

    def wait(self, timeout=None):
        """Block until all tasks finished; raises on the first task error."""
        if not self._done.wait(timeout):
            raise TimeoutError("job did not complete within {}s".format(timeout))
        if self.error is not None:
            raise RuntimeError("job failed:\n{}".format(self.error))
        return self.results

    def wait_settled(self, timeout=None):
        """Block until EVERY task reached a terminal state (ok, failed, or
        skipped) — unlike :meth:`wait`, which fires on the *first* failure
        while sibling tasks may still be in flight.  The retry machinery
        needs the settled view: retrying a partition whose original task is
        still running would double-feed its rows.
        """
        deadline = None if timeout is None else time.time() + timeout
        while True:
            with self._lock:
                if self._completed >= self.num_tasks:
                    return
            if deadline is not None and time.time() > deadline:
                raise TimeoutError(
                    "job did not settle within {}s".format(timeout))
            time.sleep(0.05)

    def failed_tasks(self):
        """``[(task_index, error_string), ...]`` for tasks that failed or
        were skipped; call after :meth:`wait_settled`."""
        with self._lock:
            return [(i, e) for i, e in enumerate(self.task_errors)
                    if e is not None]


# ---------------------------------------------------------------------------
# LocalBackend: executor worker process main loop
# ---------------------------------------------------------------------------

def _executor_main(executor_index, workdir, conn, env_overrides):
    """Long-lived executor process: apply env, chdir, serve tasks over a pipe.

    Tasks arrive as ``(task_id, pickled_fn, partition_items)``; results return
    as ``(task_id, ok, result_or_traceback)``.  Environment overrides are
    applied *before* any task runs so that e.g. ``JAX_PLATFORMS`` is set before
    the first ``import jax`` in user code.
    """
    os.environ.update(env_overrides or {})
    os.makedirs(workdir, exist_ok=True)
    os.chdir(workdir)
    import threading as _threading

    from tensorflowonspark_tpu import fault

    _threading.current_thread().name = "executor-{}".format(executor_index)
    # Resolved once per executor (counters are per-process).  Note: specs
    # targeted with ``executor_id`` resolve to NULL here — the executor-id
    # file doesn't exist until a node's start task writes it — so target
    # executor-loss faults via ``env_per_executor`` instead.
    injector = fault.from_env()
    while True:
        try:
            msg = conn.recv()
        except EOFError:
            break
        if msg is None:  # backend shutdown
            break
        task_id, fn_bytes, items = msg
        try:
            fn = cloudpickle.loads(fn_bytes)
            result = fn(iter(items))
            if result is not None and not isinstance(result, (list, tuple)):
                result = list(result)  # drain generators inside the executor
            conn.send((task_id, True, result))
        except Exception:
            conn.send((task_id, False, traceback.format_exc()))
        injector.on_task()  # kill_after_tasks: die AFTER serving N tasks


class LocalBackend(object):
    """Built-in standalone cluster: N long-lived executor processes on this host.

    Args:
      num_executors: number of executor processes.
      env: base environment overrides applied in every executor before the
        first task (e.g. ``{"JAX_PLATFORMS": "cpu"}`` for tests).
      env_per_executor: optional list of per-executor override dicts (e.g. to
        give exactly one executor the real TPU and the rest CPU).
      workdir_root: parent directory for per-executor working dirs (a fresh
        temp dir by default); each executor gets ``<root>/executor-<i>``, its
        own cwd, which is what makes the executor-id file handshake work.
    """

    #: Per-task outcomes (JobHandle.task_errors) are real here, so the
    #: driver's supervised feed retry can re-dispatch failed partitions.
    supports_task_retry = True

    #: The driver's elastic recovery can ask this backend to spawn a FRESH
    #: executor process into a dead node's freed roster slot
    #: (:meth:`provision_replacement` + :meth:`run_on`).
    supports_replacement = True

    def __init__(self, num_executors, env=None, env_per_executor=None, workdir_root=None):
        self.num_executors = num_executors
        self._owns_root = workdir_root is None
        self.workdir_root = workdir_root or tempfile.mkdtemp(prefix="tfos_tpu_local_")
        self._ctx = get_context("spawn")
        self._base_env = dict(env or {})
        self._procs = []
        self._conns = []
        self._free = _queue.Queue()
        self._stopped = False
        self._excluded = set()  # executor indices fenced off from scheduling
        self._lock = threading.Lock()  # guards _procs/_conns growth
        _live_backends.add(self)
        for i in range(num_executors):
            overrides = dict(env or {})
            if env_per_executor:
                overrides.update(env_per_executor[i] or {})
            self._spawn_executor(i, overrides)
            self._free.put(i)

    def _spawn_executor(self, i, overrides):
        parent_conn, child_conn = self._ctx.Pipe()
        proc = self._ctx.Process(
            target=_executor_main,
            args=(
                i,
                os.path.join(self.workdir_root, "executor-{}".format(i)),
                child_conn,
                overrides,
            ),
            name="local-executor-{}".format(i),
        )
        proc.start()
        child_conn.close()
        self._procs.append(proc)
        self._conns.append(parent_conn)

    # -- scheduling -------------------------------------------------------

    def _run_one(self, executor_index, task_id, fn_bytes, items, handle):
        conn = self._conns[executor_index]
        try:
            conn.send((task_id, fn_bytes, items))
            # recv with a LIVENESS poll, not a bare recv: an executor whose
            # task spawned children (every node runtime forks a manager
            # server) leaves those children holding a dup of the pipe fd,
            # so a SIGKILLed executor never EOFs the pipe — the job would
            # wedge forever instead of failing (observed: vanished-executor
            # shutdown hang).
            while not conn.poll(1.0):
                if not self._procs[executor_index].is_alive():
                    if conn.poll(0.5):
                        break  # final response raced with process exit
                    raise EOFError("executor process died")
            rid, ok, payload = conn.recv()
            assert rid == task_id
            handle._task_done(task_id, ok, payload)
        except (EOFError, OSError):
            if self._stopped:
                return
            handle._task_done(
                task_id,
                False,
                "executor {} died while running task {} (exitcode={})".format(
                    executor_index, task_id, self._procs[executor_index].exitcode
                ),
            )
        finally:
            if (self._procs[executor_index].is_alive()
                    and executor_index not in self._excluded):
                self._free.put(executor_index)

    def exclude(self, executor_index):
        """Fence an executor off from future scheduling (liveness monitor:
        its node process died, so tasks landing there would feed a corpse).
        In-flight tasks finish/fail on their own; the slot is simply never
        returned to the free pool."""
        if 0 <= executor_index < self.num_executors:
            self._excluded.add(executor_index)
            logger.warning("executor %d excluded from scheduling", executor_index)
            from tensorflowonspark_tpu import telemetry
            telemetry.get_tracer().instant("backend/executor_excluded",
                                           executor_id=executor_index)

    def provision_replacement(self, env=None):
        """Spawn a FRESH executor process for elastic recovery; returns its
        executor index (a brand-new identity — never a recycled index, so
        the liveness monitor's zombie fence on the dead executor keeps
        holding).  The new executor gets its own working directory and does
        NOT enter the free pool until its first task (the replacement start
        task dispatched via :meth:`run_on`) completes."""
        from tensorflowonspark_tpu import telemetry
        with telemetry.get_tracer().span("backend/provision_replacement"):
            with self._lock:
                if self._stopped:
                    # A liveness monitor racing teardown must not spawn an
                    # executor nobody will ever stop.
                    raise RuntimeError("backend stopped; no replacements")
                i = len(self._procs)
                overrides = dict(self._base_env)
                overrides.update(env or {})
                self._spawn_executor(i, overrides)
                self.num_executors = len(self._procs)
        logger.warning("provisioned replacement executor %d", i)
        return i

    def run_on(self, executor_index, fn, items):
        """Dispatch one task DIRECTLY onto ``executor_index``, bypassing the
        free pool (elastic recovery must land the replacement start task on
        the replacement executor — any other executor's working dir already
        hosts a node).  Returns a single-task :class:`JobHandle`; when the
        task finishes, the executor joins the free pool for ordinary
        scheduling (``_run_one``'s finally)."""
        handle = JobHandle(1)
        fn_bytes = cloudpickle.dumps(fn)
        t = threading.Thread(
            target=self._run_one,
            args=(executor_index, 0, fn_bytes, list(items), handle),
            name="task-on-{}".format(executor_index),
            daemon=True,
        )
        t.start()
        return handle

    def _live_executors(self):
        return [i for i, p in enumerate(self._procs)
                if p.is_alive() and i not in self._excluded]

    def foreach_partition_async(self, partitions, fn):
        """Dispatch ``fn(iter(partition))`` per partition onto free executors."""
        handle = JobHandle(len(partitions))
        fn_bytes = cloudpickle.dumps(fn)

        def _dispatch():
            threads = []
            for task_id, items in enumerate(partitions):
                if handle.error is not None:
                    # Job-level cancel: a sibling task already failed, so
                    # don't keep feeding the failed job's remaining tasks to
                    # executors (wait() has raised; stop() may be imminent).
                    # In-flight tasks finish on their own.
                    handle._task_done(
                        task_id, False,
                        "task skipped: job cancelled after an earlier task "
                        "failure")
                    continue
                # Poll the free queue instead of blocking forever: a dead or
                # excluded executor's slot never returns, so a bare get()
                # would starve the dispatcher once nodes start dying.
                executor_index = None
                while executor_index is None:
                    try:
                        executor_index = self._free.get(timeout=1.0)
                    except _queue.Empty:
                        if self._stopped:
                            break
                        if not self._live_executors():
                            break  # no executor can ever serve this task
                        continue
                    if (executor_index in self._excluded
                            or not self._procs[executor_index].is_alive()):
                        executor_index = None  # drop the stale slot token
                if executor_index is None:
                    handle._task_done(
                        task_id, False,
                        "backend stopped" if self._stopped else
                        "task {} unschedulable: no live executors remain "
                        "(all died or were excluded)".format(task_id))
                    continue
                if self._stopped:
                    handle._task_done(task_id, False, "backend stopped")
                    continue
                t = threading.Thread(
                    target=self._run_one,
                    args=(executor_index, task_id, fn_bytes, list(items), handle),
                    name="task-{}".format(task_id),
                    daemon=True,
                )
                t.start()
                threads.append(t)

        threading.Thread(target=_dispatch, name="job-dispatch", daemon=True).start()
        return handle

    def foreach_partition(self, partitions, fn, timeout=None):
        self.foreach_partition_async(partitions, fn).wait(timeout)

    def map_partitions(self, partitions, fn, timeout=None):
        """Run ``fn`` per partition and return the list of per-partition results."""
        return self.foreach_partition_async(partitions, fn).wait(timeout)

    def stop(self):
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            conns = list(self._conns)
            procs = list(self._procs)
        _live_backends.discard(self)
        for conn in conns:
            try:
                conn.send(None)
            except OSError:
                pass
        for proc in procs:
            proc.join(timeout=5)
            if proc.is_alive():
                proc.terminate()
                proc.join(timeout=5)
                if proc.is_alive():
                    proc.kill()
                    proc.join(timeout=1)
        if self._owns_root:
            shutil.rmtree(self.workdir_root, ignore_errors=True)


# ---------------------------------------------------------------------------
# SparkBackend: adapter over a live SparkContext (requires pyspark)
# ---------------------------------------------------------------------------

class SparkBackend(object):
    """Adapter over ``pyspark.SparkContext`` matching the backend contract.

    Deployment-equivalent to the reference: the "start job" is
    ``sc.parallelize(range(n), n).foreachPartition(fn)`` on a background thread
    (reference ``TFCluster.py:312-329``) and feed jobs are ``rdd.foreachPartition``
    / ``rdd.mapPartitions`` (reference ``TFCluster.py:92,113``).  Requires one
    task slot per executor, exactly like the reference
    (``TFSparkNode.py:110-115``).

    ``partitions`` arguments may be RDDs (used as-is) or lists (parallelized).

    Elastic recovery on Spark is **Spark's own**: when an executor dies,
    Spark re-runs its failed start/feed tasks on another executor
    (``spark.task.maxFailures``), so a replacement node "re-lands" with the
    task rather than via :meth:`LocalBackend.provision_replacement` — the
    re-run start task registers from its fresh executor and claims the dead
    node's released ``(job_name, task_index)`` slot exactly like a built-in
    replacement would (the reservation server's admission path is backend
    agnostic; only *who spawns the process* differs).  The driver therefore
    does not request replacements here (``supports_replacement = False``).
    """

    #: Spark only reports job-level outcomes to the driver (task retries are
    #: Spark's own); the supervised feed retry therefore skips this backend.
    supports_task_retry = False

    #: Replacement processes come from Spark's task retry (see class doc),
    #: not from a driver-side provisioning call.
    supports_replacement = False

    def __init__(self, sc, num_executors=None):
        import pyspark  # gated: only needed when this backend is chosen

        assert isinstance(sc, pyspark.SparkContext)
        self.sc = sc
        self.num_executors = num_executors or int(
            sc.getConf().get("spark.executor.instances", "1")
        )

    def _to_rdd(self, partitions):
        if hasattr(partitions, "foreachPartition"):  # already an RDD
            return partitions
        flat = [item for part in partitions for item in part]
        return self.sc.parallelize(flat, len(partitions))

    def foreach_partition_async(self, partitions, fn):
        rdd = self._to_rdd(partitions)
        handle = JobHandle(rdd.getNumPartitions())
        # uuid, not id(): a freed handle's address can be reused, and a
        # recycled group name would let statusTracker count a PRIOR job's
        # completed tasks into this handle's progress.
        import uuid

        job_group = "tfos-{}".format(uuid.uuid4().hex)

        def _run():
            # Job group scopes the statusTracker queries below to this job
            # (setJobGroup is thread-local, so it must be set in the thread
            # that triggers the action).
            self.sc.setJobGroup(job_group, "tensorflowonspark_tpu job")
            try:
                rdd.foreachPartition(fn)
                handle._finish_ok()
            except Exception:
                handle._task_done(0, False, traceback.format_exc())

        t = threading.Thread(target=_run, name="spark-job", daemon=True)
        t.start()
        threading.Thread(target=self._track_progress,
                         args=(job_group, handle),
                         name="spark-job-progress", daemon=True).start()
        return handle

    def _track_progress(self, job_group, handle):
        """Feed per-task completion counts into the JobHandle while the job
        runs (reference statusTracker active-task polling,
        ``TFCluster.py:152-167``).

        Without this, ``_completed`` would only move when the WHOLE job ends
        — and a job whose ps/evaluator tasks park forever never ends, so
        FILES-mode shutdown (which waits for ``_completed >= num_workers``)
        would spin until the SIGALRM watchdog.
        """
        while not handle.done():
            try:
                st = self.sc.statusTracker()
                completed = 0
                for job_id in st.getJobIdsForGroup(job_group):
                    info = st.getJobInfo(job_id)
                    if info is None:
                        continue
                    for stage_id in info.stageIds:
                        si = st.getStageInfo(stage_id)
                        if si is not None:
                            completed += si.numCompletedTasks
                handle._set_progress(completed)
            except Exception:
                logger.debug("statusTracker poll failed", exc_info=True)
            time.sleep(1)

    def foreach_partition(self, partitions, fn, timeout=None):
        self.foreach_partition_async(partitions, fn).wait(timeout)

    def map_partitions(self, partitions, fn, timeout=None):
        rdd = self._to_rdd(partitions)
        return rdd.mapPartitions(lambda it: [fn(it)]).collect()

    def stop(self):
        pass  # the caller owns the SparkContext's lifecycle

    @property
    def default_fs(self):
        """Filesystem defaultFS from the Hadoop conf (reference TFCluster.py:269-272)."""
        return self.sc._jsc.hadoopConfiguration().get("fs.defaultFS")
