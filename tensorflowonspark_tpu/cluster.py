"""Driver-side cluster lifecycle API (reference ``TFCluster.py``).

``run()`` turns a backend's executors into a JAX/TPU cluster: it computes the
role template, starts the rendezvous server, launches one long-running node
task per executor, waits for all nodes to register, and returns a
:class:`TPUCluster` whose ``train/inference/shutdown`` drive the data plane
(reference call stacks SURVEY §3.1-§3.5).

Input modes (reference ``TFCluster.py:41-44``):

- ``InputMode.FILES``  (reference name ``TENSORFLOW``): nodes read their data
  directly from shared storage; the cluster only orchestrates lifecycle.
- ``InputMode.SPARK``: the backend pushes dataset partitions through
  per-executor queues into the nodes (feed jobs with backpressure).
"""

import logging
import os
import random
import signal
import sys
import threading
import time
import uuid

from tensorflowonspark_tpu import backend as backend_mod
from tensorflowonspark_tpu import compilecache as compilecache_mod
from tensorflowonspark_tpu import node, reservation
from tensorflowonspark_tpu import telemetry as telemetry_mod

logger = logging.getLogger(__name__)


class InputMode(object):
    """How data reaches the nodes (reference ``TFCluster.py:41-44``)."""

    TENSORFLOW = 0  # reference-compat alias for FILES
    FILES = 0       # nodes read files from shared storage themselves
    SPARK = 1       # backend pushes dataset partitions via queues


class TPUCluster(object):
    """Handle for a running cluster (reference ``TFCluster`` object,
    ``TFCluster.py:29-207``)."""

    def __init__(self, backend, cluster_meta, cluster_info, input_mode,
                 server, start_job, tf_status, queues, observatory=None,
                 profiling=None, watchtower=None, autopilot=None,
                 remediator=None):
        self.backend = backend
        self.cluster_meta = cluster_meta
        self.cluster_info = cluster_info
        self.input_mode = input_mode
        self.server = server
        self.start_job = start_job
        self.tf_status = tf_status
        self.queues = queues
        # optional observatory.ObservatoryServer (cluster.run(observatory=
        # True)): live /metrics + /status HTTP endpoint; stopped with the
        # cluster on every shutdown path (see _latch_telemetry)
        self.observatory = observatory
        # optional profiling.CaptureCoordinator (rides the observatory
        # flag): .trigger() captures device traces from the driver without
        # going through HTTP; artifacts land under <log_dir>/profiles
        self.profiling = profiling
        # optional watchtower.Watchtower (rides the observatory flag):
        # streaming straggler/anomaly detection over the sample ring;
        # stopped before the observatory so the final journal flush and
        # alert-count latch land in tf_status (see _latch_telemetry)
        self.watchtower = watchtower
        # optional autopilot.Autopilot (cluster.run(autopilot=True)): the
        # closed-loop performance controller; stopped FIRST on shutdown so
        # its final journal snapshot and action tallies precede the
        # watchtower/observatory teardown (see _latch_telemetry)
        self.autopilot = autopilot
        # optional remediator.Remediator (cluster.run(remediator=True)):
        # the topology action plane over admitted watchtower alerts;
        # stopped before everything else on shutdown — its subprocess
        # pools (scale-out workers/replicas) must die before the
        # dispatcher/roster they talk to (see _latch_telemetry)
        self.remediator = remediator

    # -- data plane -------------------------------------------------------

    def train(self, data, num_epochs=1, feed_timeout=600, qname="input",
              chunk_size=1024, retry_policy=None):
        """Feed partitioned data for training (InputMode.SPARK only;
        reference ``TFCluster.py:61-92``).

        ``data`` may be:
        - a list of partitions (built-in backend) or an RDD (Spark backend);
          epochs repeat **executor-side** — each feed task replays its
          partition's packed chunks ``num_epochs`` times from an
          executor-local cache, so the driver ships every row exactly once
          (the reference re-shipped each epoch via
          ``sc.union([rdd]*num_epochs)``, ``TFCluster.py:88-91``);
        - a Spark Streaming DStream: every micro-batch RDD is fed as its own
          feed job until STOP (reference DStream branch, ``TFCluster.py:81-83``;
          pair with ``shutdown(ssc=...)``);
        - an *iterator/generator of partitions* for streaming without Spark:
          fed until exhausted or a STOP is requested.

        ``chunk_size`` governs feed amortization: rows travel in columnar
        chunks of this many rows (see ``node.train``).

        ``retry_policy``: optional
        :class:`~tensorflowonspark_tpu.fault.RetryPolicy` supervising the
        feed job (list-of-partitions data only): after ALL tasks settle,
        partitions whose tasks failed retryably (dead node/executor, drain
        timeout, cancelled sibling — see ``fault.RETRYABLE_PATTERNS``) are
        re-dispatched with backoff onto the live executors; the surviving
        nodes re-consume them from their own queues.  User-code failures
        stay fatal.  RDD/DStream/iterator data ignores the policy (Spark
        applies its own task-level retries there).
        """
        logger.info("Feeding training data")
        assert self.input_mode == InputMode.SPARK, \
            "train() feeding requires InputMode.SPARK"
        assert num_epochs >= 0
        fn = node.train(self.cluster_info, self.cluster_meta, qname,
                        feed_timeout, chunk_size, max(num_epochs, 1))
        if hasattr(data, "foreachRDD"):  # Spark Streaming DStream
            # Streaming has no epochs: feed each micro-batch once.
            fn = node.train(self.cluster_info, self.cluster_meta, qname,
                            feed_timeout, chunk_size)
            cluster = self

            def _feed_batch(rdd):
                # Runs on the streaming scheduler thread, once per interval.
                # After STOP, micro-batches keep arriving until the user's
                # awaitTermination loop (shutdown(ssc=...)) stops the
                # context; don't feed them into terminating nodes.
                if not cluster.server.done:
                    try:
                        rdd.foreachPartition(fn)
                    except Exception as e:
                        # scheduler-thread failure never reaches the driver
                        # thread: latch it so shutdown(ssc=...) exits 1
                        cluster._latch_error(e)
                        raise

            data.foreachRDD(_feed_batch)
        elif hasattr(data, "__next__"):  # streaming source: unbounded partitions
            # Streaming has no epochs: feed each partition once.
            fn = node.train(self.cluster_info, self.cluster_meta, qname,
                            feed_timeout, chunk_size)
            try:
                for part in data:
                    if self.server.done:
                        logger.info("STOP requested; ending streaming feed")
                        break
                    self.backend.foreach_partition([part], fn)
            except Exception as e:
                self._latch_error(e)
                raise
        elif hasattr(data, "foreachPartition"):  # Spark RDD
            if retry_policy is not None:
                logger.info("retry_policy ignored for RDD data: Spark "
                            "retries failed tasks itself")
            self._feed_or_latch(data, fn)
        else:
            # Retries rebuild the closure from the CURRENT roster: after a
            # replacement admission the dead node's cluster_info entry is
            # gone and the replacement's (new executor id, new manager
            # address) is in — a stale closure could not route a partition
            # that lands on the replacement executor.
            def _fn_factory():
                return node.train(self.cluster_info, self.cluster_meta,
                                  qname, feed_timeout, chunk_size,
                                  max(num_epochs, 1))

            self._feed_or_latch(list(data), fn, retry_policy, _fn_factory)

    def _feed_or_latch(self, partitions, fn, retry_policy=None,
                       fn_factory=None):
        """Dispatch a feed job; a failure (user-code error OR a consumer
        that died without one — e.g. OOM-killed, surfaced as the feeder's
        feed_timeout) is latched into ``tf_status`` so a later
        ``shutdown()`` still exits non-zero (reference ``tf_status``
        error propagation, ``TFCluster.py:177-181``)."""
        try:
            if retry_policy is not None:
                self._dispatch_with_retry(partitions, fn, retry_policy,
                                          fn_factory)
            else:
                self.backend.foreach_partition(partitions, fn)
        except Exception as e:
            self._latch_error(e)
            raise

    def _await_replacement(self, timeout=30):
        """After a node death, give the elastic replacement a bounded window
        to claim the freed slot and re-complete the roster, then refresh
        ``cluster_info`` in place.  Returns True if the roster changed (a
        retry must rebuild its feed closure); an unfilled roster just means
        the retry shrinks onto the survivors — PR-1 semantics."""
        with telemetry_mod.get_tracer().span(
                "cluster/replacement_wait", timeout_secs=timeout):
            refilled = self.server.reservations.wait(timeout=timeout)
        if not refilled:
            logger.warning(
                "no replacement admitted within %.0fs (released slots: %s); "
                "retrying on the surviving nodes only", timeout,
                self.server.reservations.released_slots())
        info = self.server.reservations.get()
        info.sort(key=node._sort_key)
        changed = info != self.cluster_info
        if changed:
            self.cluster_info[:] = info
            logger.info(
                "roster refreshed at generation %d: %s",
                self.server.reservations.generation,
                [(n["job_name"], n["task_index"], n["executor_id"])
                 for n in info])
        return changed

    def _dispatch_with_retry(self, partitions, fn, policy, fn_factory=None):
        """Supervised feed dispatch: wait for the job to SETTLE (every task
        terminal — retrying while a sibling is still feeding would
        double-ship its partition), then re-dispatch only the failed
        partitions, with the policy's backoff, while every failure stays
        retryable and attempts remain.  When the liveness monitor admitted a
        replacement node in the meantime, the retry waits for its admission
        and re-dispatches onto the refreshed roster — failed partitions land
        on the replacement (or the survivors) instead of only shrinking."""
        if not getattr(self.backend, "supports_task_retry", False):
            # Job-level backends (Spark) can't observe per-partition task
            # outcomes, and re-running the whole job would double-feed the
            # partitions that succeeded; Spark's own task retries cover
            # these deployments.
            logger.info("backend %s has no per-task outcome visibility; "
                        "dispatching unsupervised",
                        type(self.backend).__name__)
            self.backend.foreach_partition(partitions, fn)
            return
        tracer = telemetry_mod.get_tracer()
        parts = list(partitions)
        pending = list(range(len(parts)))  # indices into parts
        for attempt in range(policy.max_attempts):
            with tracer.span("cluster/dispatch", attempt=attempt + 1,
                             partitions=len(pending)):
                handle = self.backend.foreach_partition_async(
                    [parts[i] for i in pending], fn)
                handle.wait_settled()
                failed = handle.failed_tasks()
            if not failed:
                return
            errors = [e for _, e in failed]
            fatal = [e for e in errors if not policy.is_retryable(e)]
            if fatal or attempt + 1 >= policy.max_attempts:
                raise RuntimeError("feed job failed{}:\n{}".format(
                    "" if fatal else
                    " after {} attempts".format(policy.max_attempts),
                    (fatal or errors)[0]))
            delay = policy.backoff(attempt)
            logger.warning(
                "feed job: %d of %d partition task(s) failed retryably; "
                "retrying in %.1fs (attempt %d/%d). First error:\n%s",
                len(failed), len(pending), delay, attempt + 2,
                policy.max_attempts, errors[0])
            tracer.instant("cluster/retry", attempt=attempt + 1,
                           failed=len(failed), delay_secs=delay)
            time.sleep(delay)
            if (self.tf_status.get("dead_nodes")
                    and self._await_replacement()
                    and fn_factory is not None):
                fn = fn_factory()
            pending = [pending[i] for i, _ in failed]
        raise AssertionError("unreachable")  # pragma: no cover

    def _latch_error(self, exc):
        if "error" not in self.tf_status:
            self.tf_status["error"] = "{}: {}".format(
                type(exc).__name__, exc)

    def metrics_snapshot(self):
        """Per-node feed-plane counters carried by heartbeats, plus the
        cluster-wide aggregate (``_hwm``/``_max`` keys merge by max, the
        rest sum).  Live while the cluster runs; ``shutdown()`` latches the
        final snapshot into ``tf_status["telemetry"]``."""
        return self.server.metrics_snapshot()

    def _latch_telemetry(self):
        """Latch the final metrics aggregate into ``tf_status`` and flush
        the driver's trace buffer.  Runs on every shutdown path, including
        the error exits — a failed run's timeline is the one you want."""
        try:
            snap = self.server.metrics_snapshot()
            if snap.get("nodes"):
                self.tf_status.setdefault("telemetry", snap)
                # slow-request exemplars ride serving heartbeats; latch the
                # cluster-wide worst offenders so a finished run still names
                # the requests that blew its tail latency
                from tensorflowonspark_tpu import observatory as observatory_mod

                slow = observatory_mod.collect_slow(snap)
                if slow:
                    self.tf_status.setdefault("serving_slow", slow)
        except Exception:
            logger.debug("telemetry latch failed", exc_info=True)
        if self.remediator is not None:
            # stop the action plane before every other controller: its
            # spawned subprocesses (scale-out feed workers / serving
            # replicas) must drain while the dispatcher and roster they
            # talk to still exist, and the action tallies belong in
            # tf_status next to the telemetry latch
            try:
                self.remediator.stop()
                counts = self.remediator.action_counts()
                if counts:
                    self.tf_status.setdefault("remediations", counts)
            except Exception:
                logger.debug("remediator stop failed", exc_info=True)
            telemetry_mod.unregister_flight_source("remediations")
        if self.autopilot is not None:
            # stop the controller before the rule engine that feeds it
            # hints: the final journal snapshot and the action tallies
            # belong in tf_status next to the telemetry latch
            try:
                self.autopilot.stop()
                counts = self.autopilot.action_counts()
                if counts:
                    self.tf_status.setdefault("autopilot", counts)
            except Exception:
                logger.debug("autopilot stop failed", exc_info=True)
        if self.watchtower is not None:
            # stop the rule engine first: its final tick + journal flush
            # must see the closing metrics, and the alert tallies belong in
            # tf_status next to the telemetry latch
            try:
                self.watchtower.stop()
                counts = self.watchtower.alert_counts()
                if counts:
                    self.tf_status.setdefault("alerts", counts)
            except Exception:
                logger.debug("watchtower stop failed", exc_info=True)
            telemetry_mod.unregister_flight_source("sample_ring_tail")
            telemetry_mod.unregister_flight_source("alerts")
        if self.observatory is not None:
            # exporter outlives the nodes (scrapes tolerate node death) but
            # not the cluster handle; stop is idempotent across the several
            # shutdown paths that reach this latch
            try:
                self.observatory.stop()
            except Exception:
                logger.debug("observatory stop failed", exc_info=True)
        telemetry_mod.get_tracer().flush()

    def inference(self, data, qname="input", chunk_size=1024):
        """Feed data for inference, returning per-item results (reference
        ``TFCluster.py:94-113``).  Results preserve partition order; the
        1:1 item/result contract is enforced by the node feeder."""
        logger.info("Feeding inference data")
        assert self.input_mode == InputMode.SPARK, \
            "inference() feeding requires InputMode.SPARK"
        fn = node.inference(self.cluster_info, self.cluster_meta, qname,
                            chunk_size=chunk_size)
        try:
            results = self.backend.map_partitions(data, fn)
            if hasattr(results, "collect"):  # Spark path returns an RDD-like
                return results
            return [item for part in results if part for item in part]
        except Exception as e:
            self._latch_error(e)
            raise

    # -- lifecycle --------------------------------------------------------

    def shutdown(self, ssc=None, grace_secs=0, timeout=259200):
        """Stop the cluster and surface any node errors (reference
        ``TFCluster.py:115-200``).

        For Spark Streaming apps pass ``ssc``: blocks in an
        ``awaitTerminationOrTimeout`` loop until an external STOP reaches
        the reservation server, then stops the StreamingContext gracefully
        (reference ``TFCluster.py:145-151``).
        For FILES mode, waits for worker node tasks to finish their user fn
        first (reference statusTracker polling, ``TFCluster.py:152-167``).
        Exits the driver with status 1 if any node raised (reference
        ``TFCluster.py:177-181``) — fail-fast, so schedulers notice.
        """
        logger.info("Stopping cluster")
        # Shutdown must target the LIVE roster: an elastic replacement (or a
        # remediator eviction) may have swapped an executor since launch, and
        # poisoning through the stale entry would schedule the shutdown task
        # on an executor whose cluster_info row no longer matches its manager.
        try:
            info = self.server.reservations.get()
            info.sort(key=node._sort_key)
            if info != self.cluster_info:
                self.cluster_info[:] = info
                logger.info(
                    "shutdown targeting refreshed roster (generation %d)",
                    self.server.reservations.generation)
        except Exception:
            pass  # reservation server already gone; fall back to the snapshot
        timer = None
        if timeout > 0 and threading.current_thread() is threading.main_thread():
            # Watchdog so a hung node cannot wedge the driver forever
            # (reference SIGALRM watchdog, TFCluster.py:134-142).
            def _watchdog(signum, frame):
                logger.error("shutdown timeout after %ds; exiting", timeout)
                self.backend.stop()
                sys.exit(1)

            signal.signal(signal.SIGALRM, _watchdog)
            signal.alarm(timeout)
            timer = True

        ps_like = [n for n in self.cluster_info
                   if n["job_name"] in ("ps", "evaluator")]
        workers = [n for n in self.cluster_info
                   if n["job_name"] in ("chief", "master", "worker")]

        if ssc is not None:
            # Spark Streaming: keep the context alive until a STOP arrives
            # at the reservation server (external stop CLI or a node's
            # request_stop), then stop it gracefully (reference
            # TFCluster.py:145-151).
            while not ssc.awaitTerminationOrTimeout(1):
                if self.server.done:
                    logger.info("STOP received; stopping StreamingContext")
                    ssc.stop(stopSparkContext=False, stopGraceFully=True)
                    break

        if self.input_mode == InputMode.FILES:
            # Workers run the user fn inline in their start task; wait for
            # those tasks to complete before poisoning queues (reference
            # active-task polling, TFCluster.py:152-167).
            num_worker_tasks = len(workers)
            while not self.start_job.done():
                if self.start_job.error:
                    break
                if self.start_job._completed >= num_worker_tasks:
                    break  # all worker tasks returned; only ps-like still parked
                time.sleep(1)

        # Poison each worker's queues via a shutdown job; tasks land on free
        # (worker) executors since ps-like executors stay parked (reference
        # SPARK JOB #3, TFCluster.py:172-174).  Task placement is not
        # guaranteed, so each task reports the node it reached and we retry
        # until every worker node confirms (poisoning is idempotent).
        fn = node.shutdown(self.cluster_info, self.cluster_meta,
                           queues=self.queues, grace_secs=grace_secs)
        worker_ids = {n["executor_id"] for n in workers}
        covered = set()
        for attempt in range(3):
            pending = sorted(worker_ids - covered)
            if not pending:
                break
            try:
                results = self.backend.map_partitions(
                    [[i] for i in pending], fn,
                    timeout=grace_secs + 120)
                for part in results:
                    if part:
                        covered.add(part[0])
            except (RuntimeError, TimeoutError) as e:
                self._latch_error(e)  # first error wins: keep the root cause
                break
        else:
            missing = sorted(worker_ids - covered)
            if missing and "error" not in self.tf_status:
                # Distinguish "finished already" (benign: poisoning found no
                # node because the node completed and stopped) from a
                # VANISHED executor.  Probe each unconfirmed node's manager:
                # a reachable manager reporting finished/stopped is fine;
                # anything else means the executor died without reporting —
                # fail loudly like the reference (TFCluster.py:177-181),
                # not a warning + exit 0 a scheduler would read as success.
                from tensorflowonspark_tpu import util as util_mod

                by_id = {n["executor_id"]: n for n in workers}
                driver_ip = util_mod.get_ip_address()
                dead, unknown = [], []
                for i in missing:
                    n = by_id[i]
                    state = None
                    try:
                        from tensorflowonspark_tpu import manager as mgr_mod

                        m = mgr_mod.connect(n["addr"],
                                            bytes.fromhex(n["authkey"]))
                        state = m.get("state")
                    except Exception:
                        pass
                    if state in ("finished", "stopped"):
                        logger.info("node %d already %s; shutdown coverage "
                                    "not needed", i, state)
                        continue
                    if state is not None:
                        # The probe SUCCEEDED and the node is still live:
                        # a shutdown-coverage gap, not a dead executor —
                        # don't latch a fatal error.  'terminating' means
                        # the poison marker WAS seen (the node is draining
                        # but its result never reached the driver);
                        # 'running' means the marker never landed.
                        if state == "terminating":
                            logger.warning(
                                "node %d saw the poison marker and is still "
                                "draining (state=terminating); its shutdown "
                                "result never reached the driver", i)
                        else:
                            logger.warning(
                                "node %d alive but unresponsive to shutdown "
                                "(state=%s); its queue never saw a poison "
                                "marker — check feed partitioning", i, state)
                        continue
                    # A failed probe is only AUTHORITATIVE when the driver
                    # could have reached the manager at all: worker managers
                    # are same-host unix sockets (node.py mode='local'), so
                    # from a remote driver an unreachable socket proves
                    # nothing about the executor.
                    authoritative = (isinstance(n["addr"], (tuple, list))
                                     or n.get("host") == driver_ip)
                    (dead if authoritative else unknown).append((i, state))
                if unknown:
                    logger.warning(
                        "could not confirm shutdown of remote nodes %s and "
                        "their managers are not driver-reachable; check the "
                        "executor logs", [i for i, _ in unknown])
                if dead:
                    self._latch_error(RuntimeError(
                        "worker nodes never confirmed shutdown and are not "
                        "finished: {} (executor died or is unreachable)"
                        .format(["node {} state={}".format(i, s)
                                 for i, s in dead])))

        if "error" in self.tf_status:
            logger.error("cluster failed: %s", self.tf_status["error"])
            self._latch_telemetry()
            self.backend.stop()
            if timer:
                signal.alarm(0)
            sys.exit(1)

        # Stop ps-like nodes: the driver reaches their remote managers
        # directly and signals their control queues (reference
        # TFCluster.py:186-192).
        for n in ps_like:
            try:
                from tensorflowonspark_tpu import manager as mgr_mod

                m = mgr_mod.connect(n["addr"], bytes.fromhex(n["authkey"]))
                ctrl = m.get_queue("control")
                ctrl.put(None, block=True)
                ctrl.join()
            except Exception:
                logger.warning("failed to signal %s:%d for shutdown",
                               n["job_name"], n["task_index"], exc_info=True)

        # Wait for the start job to fully drain (reference TFCluster.py:195-200).
        try:
            self.start_job.wait(timeout=max(grace_secs, 60))
        except TimeoutError:
            logger.warning("start job did not fully drain; continuing shutdown")
        except RuntimeError as e:
            logger.error("cluster failed: %s", e)
            self._latch_telemetry()
            if timer:
                signal.alarm(0)
            sys.exit(1)

        if timer:
            signal.alarm(0)
        self._latch_telemetry()
        self.server.stop()
        logger.info("cluster stopped")

    def tensorboard_url(self):
        """URL of the cluster-managed TensorBoard, if launched (reference
        ``TFCluster.py:202-207``)."""
        for n in self.cluster_info:
            if n.get("tb_port"):
                return "http://{}:{}".format(n["host"], n["tb_port"])
        return None

    def profiler_addresses(self):
        """Per-host jax.profiler server addresses (``cluster.run(...,
        profiler=True)``); feed one to TensorBoard's profile-plugin capture
        dialog or ``jax.profiler.trace_remote``."""
        return ["{}:{}".format(n["host"], n["profiler_port"])
                for n in self.cluster_info if n.get("profiler_port")]


def run(cluster_backend, map_fun, tf_args, num_executors=None, num_ps=0,
        tensorboard=False, input_mode=InputMode.FILES, log_dir=None,
        master_node=None, reservation_timeout=600,
        queues=("input", "output", "error"), eval_node=False,
        release_port=True, profiler=False, executor_env=None,
        driver_ps_nodes=False, heartbeat_interval=5.0, heartbeat_misses=3,
        telemetry=False, telemetry_dir=None, data_service=None,
        observatory=False, observatory_port=0, watchtower=None,
        autopilot=False, remediator=False, compile_cache_dir=None):
    """Start a cluster: one long-running node task per executor (reference
    ``TFCluster.py:210-378``).

    Args:
      cluster_backend: a :mod:`~tensorflowonspark_tpu.backend` backend (or a
        ``SparkContext``, which is wrapped in a :class:`SparkBackend`).
      map_fun: user function ``fn(args, ctx)`` run on every node.
      tf_args: argparse Namespace or argv list for ``map_fun``.
      num_executors: cluster size (defaults to the backend's executor count).
      num_ps: number of long-running non-worker ("ps"-like) roles — kept for
        capability parity (reference async-PS mode, SURVEY §2.4); TPU training
        itself is synchronous.
      driver_ps_nodes: run the ps roles in daemon threads ON THE DRIVER
        instead of occupying executors (reference ``TFCluster.py:291-309``) —
        small clusters then spend every executor on workers.  Requires
        ``num_ps > 0``; the backend only needs ``num_executors - num_ps``
        task slots.
      master_node: name for the chief role (``None`` → plain ``worker`` 0 is
        chief, reference ``TFCluster.py:225,257-258``).
      eval_node: dedicate one node as ``evaluator`` (reference ``TFCluster.py:228``).
      input_mode: :class:`InputMode`.
      executor_env: env vars every node applies BEFORE any jax/TPU
        initialization — TPU/XLA perf knobs travel here (build with
        :func:`~tensorflowonspark_tpu.device_info.tpu_env`; the analog of the
        reference's GPU-thread tuning, reference ``common.py:143-166``).
      heartbeat_interval: seconds between node liveness beats to the
        reservation server (0 disables monitoring).  A node silent for
        ``heartbeat_interval * heartbeat_misses`` seconds is declared dead:
        its identity lands in ``tf_status['dead_nodes']``, a blocked
        ``await_reservations`` aborts immediately, and the executor is
        fenced off from further feed-task scheduling (built-in backend).
      heartbeat_misses: missed beats tolerated before declaring death.
      telemetry: enable the cluster-wide telemetry plane (lifecycle span
        traces, heartbeat-carried feed counters, hang flight recorder).
        Off by default: when False no telemetry files are written and the
        instrumentation reduces to no-op calls on a null tracer.
      telemetry_dir: directory for per-process trace/flight files
        (default: ``<log_dir>/telemetry``, or ``./telemetry`` without a
        log_dir).  See docs/OBSERVABILITY.md.
      data_service: dispatcher address of a disaggregated data service
        (``"host:port"``, ``(host, port)``, or a ``{"dispatcher": addr}``
        dict) — executors then read input over the network via
        ``ctx.get_service_feed(...)`` instead of reading files locally.
        See docs/DATA_SERVICE.md.
      observatory: start the driver-side HTTP observatory — ``/metrics``
        (Prometheus text exposition) and ``/status`` (JSON ``tf_status`` +
        metrics snapshot), scrapeable mid-run; per-node counter samples are
        kept in a bounded time-series ring so the exporter also derives
        ``*_per_sec`` rates.  The endpoint address lands on the returned
        cluster handle (``cluster.observatory.addr``).  Implies nothing
        about ``telemetry`` — but with telemetry off, nodes send bare
        beats and the exporter mostly shows ``tfos_nodes``; enable both
        for the full metric vocabulary.  See docs/OBSERVABILITY.md.
      observatory_port: TCP port for the observatory (0 = ephemeral).
      watchtower: streaming straggler/anomaly detection over the
        observatory's sample ring (see
        :mod:`~tensorflowonspark_tpu.watchtower`): ``None`` (default)
        enables it whenever the observatory is on, ``False`` disables it,
        a dict overrides rule thresholds key-wise (see
        ``watchtower.DEFAULT_CONFIG``).  Alerts surface on ``GET
        /alerts``, as ``tfos_alerts_total`` on ``/metrics``, as
        ``watchtower/alert`` trace instants, and in the append-only JSONL
        journal at ``<log_dir>/watchtower/journal.jsonl`` (replayable
        offline via ``scripts/metrics_replay.py``).  Suspect-node
        verdicts land in ``tf_status["suspects"]``.
      autopilot: closed-loop performance controller over the observatory's
        sample ring (see :mod:`~tensorflowonspark_tpu.autopilot`; requires
        ``observatory=True``): ``False`` (default) off, ``True`` on with
        defaults, a dict overrides controller/knob settings key-wise (see
        ``autopilot.DEFAULT_CONFIG``; ``{"dry_run": True}`` journals
        proposals without actuating).  Actuation rides the heartbeat-reply
        channel into per-node live setters (infeed prefetch depth,
        data-service queue bound / cache budget / wire codec, gateway
        batching).  Every action is journaled to
        ``<log_dir>/autopilot/journal.jsonl`` and surfaces on ``GET
        /autopilot`` plus ``tfos_autopilot_*`` counters on ``/metrics``.
        See docs/AUTOPILOT.md.
      remediator: topology action plane over admitted watchtower alerts
        (see :mod:`~tensorflowonspark_tpu.remediator`; requires
        ``observatory=True`` and the watchtower): ``False`` (default) off,
        ``True`` on with defaults, a dict overrides key-wise (see
        ``remediator.DEFAULT_CONFIG``; ``{"dry_run": True}`` journals
        proposals without actuating).  Closes the detect→act loop the
        watchtower only observes: persistent stragglers are fenced and
        replaced (graceful node-side SIGTERM drain + elastic slot
        re-admission), ``nonfinite`` crits roll training back to the last
        finite checkpoint (poisoned steps quarantined as
        ``<step>.corrupt``), sustained data-plane saturation scales feed
        workers out (``worker_spawn_argv``), and serving SLO burn scales
        gateway replicas (``serving_spawn_argv``).  Every action is
        journaled to ``<log_dir>/remediator/journal.jsonl`` and surfaces
        on ``GET /remediations`` plus ``tfos_remediation_actions_total``
        on ``/metrics``; final tallies latch into
        ``tf_status["remediations"]``.  See docs/FAULT_TOLERANCE.md.
      compile_cache_dir: warm-start compile plane
        (:mod:`~tensorflowonspark_tpu.compilecache`): every node points
        JAX's persistent compilation cache at this cluster-shared
        directory before touching any backend, so an elastic replacement
        node (which re-runs the same start closure) rejoins by
        deserializing instead of recompiling — ``train_compile_us_max``
        collapses from seconds to milliseconds and
        ``tfos_compile_cache_hit`` counts the saves on ``/metrics``.
        Falls back to the ``TFOS_COMPILE_CACHE_DIR`` env var; None with
        no env leaves the compile plane off.
    """
    if hasattr(cluster_backend, "parallelize"):  # raw SparkContext
        cluster_backend = backend_mod.SparkBackend(cluster_backend)
    num_executors = num_executors or cluster_backend.num_executors

    tdir = None
    if telemetry:
        tdir = os.path.abspath(
            telemetry_dir or os.path.join(log_dir or ".", "telemetry"))
    tracer = telemetry_mod.configure(telemetry, tdir)
    telemetry_mod.install_sigusr1()

    # Role template: {job_name: [executor_ids]} (reference TFCluster.py:250-264).
    num_workers = num_executors - num_ps - (1 if eval_node else 0)
    if num_workers <= 0:
        # ValueError, not assert: this guards USER configuration, and an
        # assert vanishes under ``python -O`` (the roster would then wedge
        # the rendezvous with zero workers ever registering).
        raise ValueError(
            "num_executors={} leaves no workers after num_ps={} eval_node={}".format(
                num_executors, num_ps, eval_node))
    executors = list(range(num_executors))
    cluster_template = {}
    if num_ps > 0:
        cluster_template["ps"] = executors[:num_ps]
        del executors[:num_ps]
    if eval_node:
        cluster_template["evaluator"] = executors[:1]
        del executors[:1]
    if master_node is None:
        cluster_template["worker"] = executors
    else:
        cluster_template[master_node] = executors[:1]
        if len(executors) > 1:
            cluster_template["worker"] = executors[1:]
    logger.info("cluster template: %s", cluster_template)

    # Shared driver-side status dict: async start-job failures land in
    # 'error' (fatal); the liveness monitor appends to 'dead_nodes'
    # (recoverable — a supervised retry may complete the run regardless);
    # replacement admissions land in 'replacements'; clean BYE reasons
    # ('done' / 'preempted') land in 'byes' keyed by executor id.
    tf_status = {}

    # The replacement path needs the start-task closure, which is built
    # AFTER the server (the closure captures cluster_meta, which carries the
    # server address) — a mutable cell bridges the ordering.
    elastic = {"start_fn": None}

    def _request_replacement(meta):
        """Elastic recovery: release the dead node's roster slot and spawn a
        fresh executor into it (built-in backend).  Returns True when a
        replacement was dispatched; False leaves the PR-1 semantics (fence
        only, roster abort on bring-up death) untouched."""
        start_fn = elastic.get("start_fn")
        if (start_fn is None
                or not getattr(cluster_backend, "supports_replacement", False)
                or meta.get("executor_id") is None
                or meta.get("job_name") is None):
            return False
        released = server.release_slot(meta["executor_id"])
        if released is None:
            return False  # died before registering: nothing to reclaim
        try:
            with tracer.span("cluster/replacement_provision",
                             dead_executor=meta["executor_id"],
                             job_name=released["job_name"],
                             task_index=released["task_index"]):
                new_index = cluster_backend.provision_replacement()
                handle = cluster_backend.run_on(
                    new_index, start_fn,
                    [{"executor_id": new_index,
                      "job_name": released["job_name"],
                      "task_index": released["task_index"]}])
        except Exception:
            logger.exception("replacement provisioning failed; the run "
                             "continues on the surviving nodes")
            return False
        desc = "executor {} replaces {} as {}:{}".format(
            new_index, meta["executor_id"], released["job_name"],
            released["task_index"])
        tf_status.setdefault("replacements", []).append(desc)
        logger.warning("elastic recovery: %s", desc)
        tracer.instant("cluster/replacement_dispatched",
                       new_executor=new_index,
                       dead_executor=meta["executor_id"],
                       job_name=released["job_name"],
                       task_index=released["task_index"])

        def _watch():
            try:
                handle.wait_settled(timeout=reservation_timeout)
            except Exception:
                pass
            failed = handle.failed_tasks()
            if failed:
                logger.error("replacement start task failed:\n%s",
                             failed[0][1])
                tf_status.setdefault("replacement_errors", []).append(
                    failed[0][1])

        threading.Thread(target=_watch, name="replacement-watch",
                         daemon=True).start()
        return True

    def _on_dead(meta, age):
        desc = ("node {}:{} (executor {}) on {} declared dead after {:.1f}s "
                "of heartbeat silence").format(
                    meta.get("job_name", "?"), meta.get("task_index", "?"),
                    meta.get("executor_id", "?"), meta.get("host", "?"), age)
        tf_status.setdefault("dead_nodes", []).append(desc)
        tracer.instant("cluster/node_dead",
                       executor_id=meta.get("executor_id"),
                       job_name=meta.get("job_name"),
                       task_index=meta.get("task_index"),
                       age_secs=round(age, 3))
        if (hasattr(cluster_backend, "exclude")
                and meta.get("executor_id") is not None):
            cluster_backend.exclude(meta["executor_id"])
        _request_replacement(meta)

    def _on_bye(executor_id, reason):
        tf_status.setdefault("byes", {})[str(executor_id)] = reason

    # Rendezvous server (reference TFCluster.py:277-279) + liveness monitor.
    server = reservation.Server(num_executors,
                                heartbeat_interval=heartbeat_interval,
                                heartbeat_misses=heartbeat_misses,
                                on_dead=_on_dead, on_bye=_on_bye)
    server_addr = server.start()

    obs = None
    profiling_coord = None
    wt = None
    pilot = None
    rem = None
    if autopilot and not observatory:
        raise ValueError("autopilot= requires observatory=True: the "
                         "controller reads the observatory's sample ring")
    if remediator and not observatory:
        raise ValueError("remediator= requires observatory=True: the action "
                         "plane consumes the watchtower's admitted alerts")
    if remediator and watchtower is False:
        raise ValueError("remediator= requires the watchtower: its admitted "
                         "alerts ARE the detect half of the detect→act loop")
    if observatory:
        from tensorflowonspark_tpu import observatory as observatory_mod
        from tensorflowonspark_tpu import profiling as profiling_mod

        # Sample ring first: the server records a timestamped copy of each
        # node's folded counters on every metrics-bearing beat, so the
        # exporter can derive rates; the HTTP endpoint reads only through
        # snapshot callables (copies), so scrapes are safe mid-run and
        # mid-node-death.
        ring = observatory_mod.SampleRing()
        server.sample_ring = ring
        # On-demand device-trace captures: GET /profile fans out through
        # the heartbeat channel and artifacts land under <log_dir>/profiles.
        profiling_coord = profiling_mod.CaptureCoordinator(
            server, os.path.abspath(
                os.path.join(log_dir or ".", "profiles")))
        server.profile_coordinator = profiling_coord

        if autopilot:
            from tensorflowonspark_tpu import autopilot as autopilot_mod

            # Actuation plane: knob pushes fan out through the
            # heartbeat-reply channel (the PROF/reregister pattern) — each
            # node drains its unseen pushes exactly once per beat and
            # applies the namespaced knobs its registered feeds claim
            # (node.apply_knobs); unclaimed names are ignored, so one
            # broadcast serves trainers, gateways, and worker relays alike.
            # A journal-armed server may already have rebuilt the
            # coordinator (full push history + drain positions) during
            # recovery — reuse it so the fleet's standing knob state
            # survives the coordinator death.
            if server.knob_coordinator is None:
                server.knob_coordinator = reservation.KnobCoordinator()
            ap_config = dict(autopilot) if isinstance(autopilot, dict) else {}
            ap_knobs = {k: dict(v)
                        for k, v in (ap_config.get("knobs") or {}).items()}
            ap_knobs.setdefault("infeed_prefetch", {})
            if "initial" not in ap_knobs["infeed_prefetch"]:
                # seed the controller with the fleet's actual starting depth
                # so the first retune doubles from reality, not a guess
                try:
                    ap_knobs["infeed_prefetch"]["initial"] = max(
                        int(os.environ.get("TFOS_INFEED_PREFETCH", "2")), 1)
                except ValueError:
                    ap_knobs["infeed_prefetch"]["initial"] = 2
            ap_config["knobs"] = ap_knobs
            # push_knobs (not the bare KnobCoordinator.push) journals each
            # retune when the server is journal-armed, so the controller's
            # standing intent rides a coordinator failover; resume_values
            # re-seeds the controller from the recovered push history.
            pilot = autopilot_mod.Autopilot(
                ring, actuator=server.push_knobs,
                snapshot_fn=server.metrics_snapshot,
                config=ap_config,
                journal_path=os.path.abspath(os.path.join(
                    log_dir or ".", "autopilot", "journal.jsonl")),
                resume_values=server.knob_coordinator.current())
            pilot.start()
            logger.info("autopilot engaged (dry_run=%s), journal at %s",
                        pilot.config["dry_run"], pilot.journal_path)

        if remediator:
            from tensorflowonspark_tpu import remediator as remediator_mod

            def _evict_straggler(executor, alert):
                # Fence + replace, in dependency order: the evict command
                # is queued FIRST (the node drains it from its next beat
                # reply and SIGTERMs itself — graceful feed drain, chief
                # emergency checkpoint, BYE), then the driver releases the
                # roster slot, excludes the executor backend-side, and
                # dispatches a replacement into the freed slot.  The
                # released node keeps beating until its drain completes
                # (only *dead* executors are fenced from the beat
                # channel), so the command always reaches it; its BYE
                # later pops the beat entry, so no death is declared and
                # no second replacement fires.
                try:
                    eid = int(executor)
                except (TypeError, ValueError):
                    eid = executor
                meta = server.reservations.find(eid)
                if meta is None:
                    raise RuntimeError(
                        "executor {} holds no reservation".format(executor))
                token = "evict-{}-{}".format(eid, int(time.time() * 1000))
                server.push_knobs({"remediator_evict": token},
                                  executor_id=eid)
                if hasattr(cluster_backend, "exclude"):
                    cluster_backend.exclude(eid)
                replaced = _request_replacement(meta)
                return {"executor": eid, "token": token,
                        "replaced": bool(replaced),
                        "job_name": meta.get("job_name"),
                        "task_index": meta.get("task_index")}

            def _rollback_poison(executor, alert):
                # Broadcast, not targeted: every trainer honours the
                # rollback — the chief's restore quarantines the poisoned
                # step(s); workers re-restore the same validated step.
                token = "rollback-{}".format(int(time.time() * 1000))
                server.push_knobs({"train_rollback": token})
                ev = (alert or {}).get("evidence") or {}
                return {"token": token,
                        "train_steps_total": ev.get("train_steps_total")}

            rem = remediator_mod.Remediator(
                ring,
                actions={"evict": _evict_straggler,
                         "rollback": _rollback_poison},
                snapshot_fn=server.metrics_snapshot,
                config=(dict(remediator) if isinstance(remediator, dict)
                        else None),
                journal_path=os.path.abspath(os.path.join(
                    log_dir or ".", "remediator", "journal.jsonl")))
            rem.start()
            logger.info("remediator engaged (dry_run=%s), journal at %s",
                        rem.dry_run, rem.journal_path)

        def _profiler_addresses():
            # lazy: the observatory starts before the roster exists, and the
            # roster can change on replacement admission
            return ["{}:{}".format(m.get("host"), m.get("profiler_port"))
                    for m in server.reservations.get()
                    if isinstance(m, dict) and m.get("profiler_port")]

        if watchtower is not False:
            from tensorflowonspark_tpu import watchtower as watchtower_mod

            def _on_suspect(executor, alert):
                # the elastic-recovery plane's consumption point: verdicts
                # accumulate here next to dead_nodes/replacements
                tf_status.setdefault("suspects", {})[str(executor)] = (
                    alert.get("rule"))

            # Admitted alerts fan out to every consumer plane: the
            # autopilot treats them as retune hints, the remediator as
            # triggers for topology actions.
            _alert_sinks = [s for s in (
                pilot.observe_alert if pilot is not None else None,
                rem.observe_alert if rem is not None else None)
                if s is not None]

            def _fan_alert(alert):
                for sink in _alert_sinks:
                    try:
                        sink(alert)
                    except Exception:
                        logger.warning("alert sink failed", exc_info=True)

            wt = watchtower_mod.Watchtower(
                ring=ring, snapshot_fn=server.metrics_snapshot,
                heartbeat_interval=heartbeat_interval,
                config=watchtower if isinstance(watchtower, dict) else None,
                journal_path=os.path.abspath(os.path.join(
                    log_dir or ".", "watchtower", "journal.jsonl")),
                on_suspect=_on_suspect, beat_ages_fn=server.beat_ages,
                coordinator_fn=server.ha_status,
                on_alert=(_fan_alert if _alert_sinks else None))
            wt.start()
            # Flight records (SIGUSR1 / stall dumps) now carry the metric
            # trajectory and alert log leading into the stall.
            telemetry_mod.register_flight_source("sample_ring_tail",
                                                 wt.ring_tail)
            telemetry_mod.register_flight_source("alerts", wt.alerts)
            if rem is not None:
                telemetry_mod.register_flight_source("remediations",
                                                     rem.actions)

        obs = observatory_mod.ObservatoryServer(
            server.metrics_snapshot, ring=ring,
            status_fn=lambda: tf_status, port=observatory_port,
            profile_fn=profiling_coord.trigger,
            profiler_addresses_fn=_profiler_addresses,
            capture_status_fn=profiling_coord.status,
            watchtower=wt, autopilot=pilot, remediator=rem,
            coordinator_fn=server.ha_status,
            beat_ages_fn=server.beat_ages)
        addr = obs.start()
        logger.info("observatory serving /metrics, /status, /profile and "
                    "/alerts at http://%s:%d", addr[0], addr[1])

    # Normalize the data-service spec to {"dispatcher": [host, port]} for
    # the JSON hop to executors (ctx.get_service_feed consumes it).  An
    # optional "codecs" preference list survives normalization so a driver
    # can pin the wire compression its consumers offer at dial.
    if data_service is not None:
        codecs = (data_service.get("codecs")
                  if isinstance(data_service, dict) else None)
        addr = (data_service.get("dispatcher")
                if isinstance(data_service, dict) else data_service)
        # "dispatcher" may be one endpoint or a LIST (primary first, warm
        # standbys at pinned ports after): a single endpoint keeps the
        # historic [host, port] JSON shape, a list becomes [[host, port],
        # ...] — ServiceFeed/FeedWorker normalize either and redial across
        # the list on a dispatcher failover.
        eps = reservation.normalize_endpoints(addr)
        if len(eps) == 1:
            data_service = {"dispatcher": [eps[0][0], int(eps[0][1])]}
        else:
            data_service = {"dispatcher": [[h, int(p)] for h, p in eps]}
        if codecs is not None:
            data_service["codecs"] = list(codecs)

    # Reservation-coordinator endpoint list for the nodes: the live
    # primary first, then any warm standbys at pre-agreed pinned ports
    # (TFOS_RS_STANDBY env: "host:port[,host:port...]").  Node-side
    # Client/HeartbeatSender redial across the list, so a coordinator
    # failover needs no re-broadcast of cluster_meta.
    server_addrs = [list(server_addr)]
    for part in (os.environ.get("TFOS_RS_STANDBY") or "").split(","):
        part = part.strip()
        if part:
            shost, _, sport = part.rpartition(":")
            server_addrs.append([shost, int(sport)])

    cluster_meta = {
        "id": "{:x}".format(random.getrandbits(64)),
        "cluster_template": cluster_template,
        "num_executors": num_executors,
        "default_fs": getattr(cluster_backend, "default_fs", "file://"),
        "server_addr": list(server_addr),
        "server_addrs": server_addrs,
        "authkey": uuid.uuid4().bytes.hex(),
        "reservation_timeout": reservation_timeout,
        "input_mode": input_mode,
        "executor_env": dict(executor_env or {}),
        "heartbeat_interval": heartbeat_interval,
        "telemetry": telemetry_mod.meta_spec(telemetry, tdir),
        "data_service": data_service,
        # Resolved on the DRIVER (env fallback included) so every node —
        # and every future replacement — shares one cache root even when
        # only the driver's environment names it.
        "compile_cache_dir": (os.path.abspath(compile_cache_dir)
                              if compile_cache_dir
                              else os.environ.get(
                                  compilecache_mod.CACHE_DIR_ENV)),
    }
    tracer.instant("cluster/start", num_executors=num_executors,
                   input_mode=str(input_mode),
                   cluster_id=cluster_meta["id"])

    # Launch the start job in the background (reference daemon thread +
    # foreachPartition, TFCluster.py:312-329): SPARK-mode workers run the user
    # fn in a background process so their task returns and frees the slot for
    # feed jobs; FILES-mode workers hold the slot for the whole run.
    background = (input_mode == InputMode.SPARK)
    start_fn = node.run(map_fun, tf_args, cluster_meta, tensorboard=tensorboard,
                        log_dir=log_dir, queues=tuple(queues),
                        background=background, release_port=release_port,
                        profiler=profiler)
    # Replacement admission re-runs this same start closure on the fresh
    # executor (the role travels as an explicit assignment item, see
    # node.run) — SPARK-mode nodes run the user fn in a background child,
    # so a replacement can join mid-run without holding a task slot.
    if background:
        elastic["start_fn"] = start_fn
    if driver_ps_nodes:
        # ps roles run in driver daemon threads (reference
        # TFCluster.py:291-309): the backend's start job covers only the
        # worker executors, so every backend slot hosts a worker.
        assert num_ps > 0, "driver_ps_nodes requires num_ps > 0"
        start_ids = list(range(num_ps, num_executors))
        ps_fn = node.run(map_fun, tf_args, cluster_meta, log_dir=log_dir,
                         queues=tuple(queues), background=background,
                         release_port=release_port, driver_local=True)

        def _start_driver_ps(node_index):
            try:
                ps_fn(iter([node_index]))
            except Exception:
                logger.exception("driver-local ps %d failed", node_index)

        for i in cluster_template["ps"]:
            threading.Thread(target=_start_driver_ps, args=(i,),
                             name="driver-ps-{}".format(i),
                             daemon=True).start()
    else:
        start_ids = list(range(num_executors))
    start_parts = [[i] for i in start_ids]
    start_job = cluster_backend.foreach_partition_async(start_parts, start_fn)

    # Propagate async start-job failures into the reservation wait (reference
    # tf_status error flag, TFCluster.py:38,321-323 + reservation.py:117-120).
    def _monitor():
        while not start_job.done():
            if start_job.error:
                break
            time.sleep(0.5)
        if start_job.error:
            tf_status["error"] = start_job.error

    threading.Thread(target=_monitor, name="start-job-monitor", daemon=True).start()

    cluster_info = server.await_reservations(
        status=tf_status, timeout=reservation_timeout)
    cluster_info.sort(key=node._sort_key)
    logger.info("cluster nodes: %s",
                [(n["job_name"], n["task_index"], n["host"]) for n in cluster_info])
    tracer.instant("cluster/ready", nodes=len(cluster_info),
                   generation=server.reservations.generation)

    # Duplicate-node sanity check (reference TFCluster.py:350-365).
    seen = set()
    for n in cluster_info:
        key = (n["host"], n["executor_id"])
        if key in seen:
            raise Exception(
                "Duplicate cluster node on executor {} of host {}: executors "
                "must provide exactly one task slot each (disable dynamic "
                "allocation / over-subscription).".format(n["executor_id"], n["host"]))
        seen.add(key)

    return TPUCluster(cluster_backend, cluster_meta, cluster_info, input_mode,
                      server, start_job, tf_status, tuple(queues),
                      observatory=obs, profiling=profiling_coord,
                      watchtower=wt, autopilot=pilot, remediator=rem)
