"""Model fleet control plane: registry, router, canary rollout, handoff.

The reference framework's L5 inference layer (``TFModel.transform``) was a
single-model batch engine — no versions, no routing, no continuous
learning.  This module is the serving v2 control plane layered over the
PR 11 gateway:

- :class:`ModelRegistry` — versioned manifest store.  Each ``(model,
  version)`` entry pins a validated export directory, its model_config,
  an optional AOT warm dir, and a lifecycle status (:data:`STATUSES`,
  ``staging -> canary -> live -> retired``).  Every mutation appends to a
  flush-per-write JSONL journal (the PR 13/16 discipline) so the registry
  rebuilds from disk after a driver crash, tolerating a torn final line.
  Concurrent publishes of the same version elect a single winner through
  an ``O_CREAT|O_EXCL`` marker file — the loser gets
  :class:`PublishConflict`, never a silent overwrite.
- :class:`FleetRouter` — the admission/routing brain split out of
  ``GatewayServer`` (ROADMAP item 4).  Maps ``(model, version-or-default)``
  to the replica set derived from the roster's ``job_name="serving"``
  registrations (replicas register with ``model``/``model_version`` meta),
  sheds with typed ``unknown_model`` / ``no_capacity`` codes, enforces a
  per-model admission budget so one hot model cannot starve the rest, and
  spreads load power-of-two-choices over healthy replicas, counting picks.
- :class:`CanaryController` — guardrails-vocabulary rollout loop.  A
  staging version is proposed as a canary on ONE replica (the
  ``serving_load_version`` knob rides the heartbeat reply, so the swap is
  a zero-recompile weight flip — see ``ModelServer.swap_export``), watched
  through the version-labeled error-rate / nonfinite windows, then
  auto-promoted to live on clean windows or auto-rolled-back on burn.
  Every stage is journaled and :func:`replay_journal` re-derives the
  decision stream offline (``metrics_replay.py`` integration).
- :func:`publish_trained` — the train-to-serve handoff: ``fit_supervised``
  exports its final validated params straight into the registry as a
  staging version, which the canary controller walks to live with no
  operator in the loop.
"""

import json
import logging
import os
import random
import threading
import time

from .guardrails import Guardrails, JsonlJournal
from .watchtower import json_safe

logger = logging.getLogger(__name__)

#: version lifecycle, in promotion order
STATUSES = ("staging", "canary", "live", "retired")

#: typed shed codes the router adds to the gateway's vocabulary
ROUTER_SHEDS = ("unknown_model", "no_capacity")


class PublishConflict(RuntimeError):
    """A concurrent publisher already won ``(model, version)``."""


class SwapRefused(ValueError):
    """A live swap was refused (incompatible params/signature — applying
    it would force a recompile or corrupt outputs)."""


def _check_name(kind, value):
    value = str(value)
    if not value or any(c in value for c in "/\\\0\n@"):
        raise ValueError("invalid {} name {!r}".format(kind, value))
    return value


def read_registry_journal(path):
    """Parse a registry journal, stopping at the first torn/garbled line.

    Unlike the watchtower journal (independent snapshot records, skipping
    a bad line is safe), registry records are ordered state transitions:
    everything AFTER a torn line is untrusted, so replay stops there.  A
    crash mid-append therefore loses at most the record being written.
    """
    records = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    logger.warning("%s: torn journal tail; replay stops at "
                                   "record %d", path, len(records))
                    break
                if isinstance(rec, dict):
                    records.append(rec)
    except FileNotFoundError:
        pass
    return records


class ModelRegistry(object):
    """Versioned model manifest store with a crash-safe JSONL journal.

    The journal at ``<root>/registry.jsonl`` is the source of truth;
    construction replays it (torn tail tolerated) into memory.  Publishes
    are made atomic across *processes* by an ``O_CREAT|O_EXCL`` marker
    under ``<root>/.published/`` — exactly one publisher of a given
    ``(model, version)`` wins, all others raise :class:`PublishConflict`.
    """

    def __init__(self, root, publisher=None, clock=time.time):
        self.root = os.path.abspath(str(root))
        os.makedirs(self.root, exist_ok=True)
        self.publisher = publisher or "pid-{}".format(os.getpid())
        self._clock = clock
        self._lock = threading.RLock()
        #: model -> {"versions": {v: entry}, "order": [v, ...], "default": v}
        self._models = {}
        self.journal_path = os.path.join(self.root, "registry.jsonl")
        fresh = not os.path.exists(self.journal_path)
        for rec in read_registry_journal(self.journal_path):
            self._apply(rec)
        self._journal = JsonlJournal(self.journal_path, owner="fleet-registry")
        if fresh:
            self._journal.write({"kind": "meta", "registry": True,
                                 "version": 1, "time": self._clock()})

    # -- journal replay ----------------------------------------------------

    def _apply(self, rec):
        kind = rec.get("kind")
        if kind == "publish":
            slot = self._models.setdefault(
                rec["model"], {"versions": {}, "order": [], "default": None})
            if rec["version"] in slot["versions"]:
                return  # duplicate journal line; first publish won
            slot["versions"][rec["version"]] = {
                k: rec.get(k) for k in
                ("model", "version", "export_dir", "model_config",
                 "warm_dir", "status", "time", "publisher")}
            slot["order"].append(rec["version"])
            if rec.get("status") == "live":
                slot["default"] = rec["version"]
        elif kind == "status":
            slot = self._models.get(rec.get("model"))
            entry = (slot or {"versions": {}})["versions"].get(
                rec.get("version"))
            if entry is None:
                return
            entry["status"] = rec["status"]
            if rec["status"] == "live":
                slot["default"] = rec["version"]
            elif slot["default"] == rec["version"]:
                slot["default"] = rec.get("default")

    # -- writes ------------------------------------------------------------

    @staticmethod
    def validate_export(export_dir):
        """An export is publishable iff its descriptor + params dir exist."""
        desc = os.path.join(export_dir, "export.json")
        params = os.path.join(export_dir, "params")
        if not os.path.isfile(desc) or not os.path.isdir(params):
            raise ValueError(
                "not a valid export (missing export.json/params): "
                "{}".format(export_dir))

    def publish(self, model, version, export_dir, model_config=None,
                warm_dir=None, status="staging", validate=True):
        """Publish ``(model, version)`` pinning ``export_dir``.  Exactly one
        concurrent publisher wins (O_EXCL marker); losers raise
        :class:`PublishConflict`.  Returns the journaled entry."""
        model = _check_name("model", model)
        version = _check_name("version", version)
        if status not in STATUSES:
            raise ValueError("bad status {!r}".format(status))
        export_dir = os.path.abspath(str(export_dir))
        if validate:
            self.validate_export(export_dir)
        marker_dir = os.path.join(self.root, ".published")
        os.makedirs(marker_dir, exist_ok=True)
        marker = os.path.join(marker_dir, "{}@{}".format(model, version))
        try:
            fd = os.open(marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            raise PublishConflict(
                "{}@{} already published".format(model, version))
        try:
            os.write(fd, self.publisher.encode())
        finally:
            os.close(fd)
        with self._lock:
            rec = {"kind": "publish", "model": model, "version": version,
                   "export_dir": export_dir,
                   "model_config": json_safe(model_config),
                   "warm_dir": warm_dir, "status": status,
                   "time": self._clock(), "publisher": self.publisher}
            self._apply(rec)
            self._journal.write(rec)
            logger.info("registry: published %s@%s (%s) -> %s", model,
                        version, status, export_dir)
            return dict(self._models[model]["versions"][version])

    def set_status(self, model, version, status, reason=None):
        """Move ``(model, version)`` to ``status``.  Promoting to ``live``
        retires the previous live version and flips the model's default;
        retiring the default clears it (callers re-promote explicitly)."""
        if status not in STATUSES:
            raise ValueError("bad status {!r}".format(status))
        with self._lock:
            slot = self._models.get(model)
            if not slot or version not in slot["versions"]:
                raise KeyError("{}@{} not in registry".format(model, version))
            if status == "live":
                prev = slot["default"]
                if prev and prev != version and (
                        slot["versions"][prev]["status"] == "live"):
                    self._write_status(model, prev, "retired",
                                       reason="superseded by {}".format(
                                           version))
            self._write_status(model, version, status, reason=reason)
            return dict(slot["versions"][version])

    def _write_status(self, model, version, status, reason=None):
        rec = {"kind": "status", "model": model, "version": version,
               "status": status, "reason": reason, "time": self._clock()}
        self._apply(rec)
        self._journal.write(rec)
        logger.info("registry: %s@%s -> %s%s", model, version, status,
                    " ({})".format(reason) if reason else "")

    # -- reads -------------------------------------------------------------

    def resolve(self, model, version=None):
        """Entry for ``(model, version-or-default)``.  ``KeyError`` when the
        model is unknown; ``LookupError`` when it has no default (no live
        version yet) and no version was pinned."""
        with self._lock:
            slot = self._models.get(model)
            if slot is None:
                raise KeyError("unknown model {!r}".format(model))
            if version is None:
                version = slot["default"]
                if version is None:
                    raise LookupError(
                        "model {!r} has no live version".format(model))
            entry = slot["versions"].get(str(version))
            if entry is None:
                raise KeyError("{}@{} not in registry".format(model, version))
            return dict(entry)

    def versions(self, model):
        """Entries of ``model`` in publish order (copies)."""
        with self._lock:
            slot = self._models.get(model, {"versions": {}, "order": []})
            return [dict(slot["versions"][v]) for v in slot["order"]]

    def default_version(self, model):
        with self._lock:
            slot = self._models.get(model)
            return slot["default"] if slot else None

    def models(self):
        with self._lock:
            return sorted(self._models)

    def snapshot(self):
        """JSON-safe full registry state (``/fleet`` surface)."""
        with self._lock:
            return {m: {"default": slot["default"],
                        "versions": [dict(slot["versions"][v])
                                     for v in slot["order"]]}
                    for m, slot in self._models.items()}

    def close(self):
        self._journal.close()


class _Lease(object):
    """Admission lease: releases the per-model in-flight slot on exit."""

    def __init__(self, router, model):
        self._router = router
        self.model = model
        self._done = False

    def release(self):
        if not self._done:
            self._done = True
            self._router._release(self.model)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class FleetRouter(object):
    """Maps ``(model, version-or-default)`` to a replica, with typed sheds.

    The replica table is fed from roster ``job_name="serving"`` rows
    (:meth:`sync_roster`) whose registrations carry ``model`` /
    ``model_version`` meta, and reconciled live from heartbeat metric
    strings as replicas swap versions (:meth:`note_version`).  Admission
    is budgeted per model (``admit``), canary traffic is split by
    version weight (``set_split``), and within a version the replica is
    chosen power-of-two-choices by in-flight depth — picks are counted
    per replica so balance is observable.
    """

    def __init__(self, registry=None, budget_per_model=256, seed=0x51EE7):
        self.registry = registry
        self.budget_per_model = int(budget_per_model)
        self._lock = threading.Lock()
        self._replicas = {}   # rid -> {model, version, addr, healthy}
        self._split = {}      # model -> {version: weight}
        self._inflight = {}   # rid -> depth
        self._model_inflight = {}
        self.picks = {}       # rid -> routed count
        self.admitted = {}    # model -> admitted count
        self.shed = {code: 0 for code in ROUTER_SHEDS}
        self._rng = random.Random(seed)

    # -- replica table -----------------------------------------------------

    def register_replica(self, replica_id, addr, model, version):
        with self._lock:
            self._replicas[replica_id] = {
                "model": str(model), "version": str(version),
                "addr": addr, "healthy": True}

    def sync_roster(self, rows):
        """Rebuild the table from roster rows (``job_name == "serving"``).
        Rows without model meta land under model ``"default"`` so pre-fleet
        replicas stay routable."""
        table = {}
        for m in rows or []:
            if not isinstance(m, dict) or m.get("job_name") != "serving":
                continue
            rid = m.get("executor_id")
            if rid is None or m.get("host") is None:
                continue
            table[rid] = {
                "model": str(m.get("model") or "default"),
                "version": str(m.get("model_version") or "0"),
                "addr": "{}:{}".format(m["host"], m["port"]),
                "healthy": True}
        with self._lock:
            for rid, row in table.items():
                old = self._replicas.get(rid)
                if old is not None:
                    row["healthy"] = old["healthy"]
            self._replicas = table

    def note_version(self, replica_id, version):
        """Record a confirmed live swap (heartbeat metrics reconcile)."""
        with self._lock:
            row = self._replicas.get(replica_id)
            if row is not None and row["version"] != str(version):
                row["version"] = str(version)

    def set_health(self, replica_id, healthy):
        with self._lock:
            row = self._replicas.get(replica_id)
            if row is not None:
                row["healthy"] = bool(healthy)

    def replicas(self, model=None, version=None, healthy_only=False):
        with self._lock:
            out = {}
            for rid, row in self._replicas.items():
                if model is not None and row["model"] != model:
                    continue
                if version is not None and row["version"] != str(version):
                    continue
                if healthy_only and not row["healthy"]:
                    continue
                out[rid] = dict(row)
            return out

    # -- canary split ------------------------------------------------------

    def set_split(self, model, weights):
        """Weighted version split for ``model`` (``{version: weight}``);
        ``None``/empty clears back to default-version routing."""
        with self._lock:
            if weights:
                self._split[model] = {str(v): float(w)
                                      for v, w in weights.items() if w > 0}
            else:
                self._split.pop(model, None)

    # -- admission ---------------------------------------------------------

    def admit(self, model):
        """Admission lease for one request on ``model``; raises a typed
        ``no_capacity`` shed when the model's budget is exhausted (a hot
        model saturates its own budget, not the fleet's)."""
        from . import gateway
        with self._lock:
            depth = self._model_inflight.get(model, 0)
            if depth >= self.budget_per_model:
                self.shed["no_capacity"] += 1
                raise gateway.OverloadError(
                    "no_capacity",
                    "model {} at its admission budget ({} in flight)".format(
                        model, depth))
            self._model_inflight[model] = depth + 1
            self.admitted[model] = self.admitted.get(model, 0) + 1
        return _Lease(self, model)

    def _release(self, model):
        with self._lock:
            self._model_inflight[model] = max(
                0, self._model_inflight.get(model, 1) - 1)

    # -- routing -----------------------------------------------------------

    def _choose_version(self, model):
        """Caller holds the lock.  Split weights win; else registry
        default; else the single version present in the table."""
        split = self._split.get(model)
        if split:
            # drop weights whose version has no healthy replica so a
            # mid-swap canary never blackholes traffic
            viable = {v: w for v, w in split.items()
                      if any(r["model"] == model and r["version"] == v
                             and r["healthy"]
                             for r in self._replicas.values())}
            if viable:
                total = sum(viable.values())
                roll = self._rng.random() * total
                for v, w in viable.items():
                    roll -= w
                    if roll <= 0:
                        return v
                return next(iter(viable))
        if self.registry is not None:
            try:
                default = self.registry.default_version(model)
            except Exception:
                default = None
            if default:
                return default
        versions = {r["version"] for r in self._replicas.values()
                    if r["model"] == model and r["healthy"]}
        return next(iter(versions)) if len(versions) == 1 else None

    def route(self, model, version=None):
        """Pick a healthy replica for ``(model, version-or-default)``.

        Returns ``(replica_id, addr, version)``.  Sheds typed:
        ``unknown_model`` when neither the table nor the registry knows
        the model, ``no_capacity`` when the model is known but has no
        healthy replica of a routable version.
        """
        from . import gateway
        with self._lock:
            known = any(r["model"] == model
                        for r in self._replicas.values())
            if not known and self.registry is not None:
                known = model in self.registry.models()
            if not known:
                self.shed["unknown_model"] += 1
                raise gateway.OverloadError(
                    "unknown_model", "no such model {!r}".format(model))
            want = str(version) if version is not None else (
                self._choose_version(model))
            cands = [(rid, row) for rid, row in self._replicas.items()
                     if row["model"] == model and row["healthy"]
                     and (want is None or row["version"] == want)]
            if not cands and want is not None and version is None:
                # default version drained mid-swap: serve whatever healthy
                # replicas the model still has rather than shedding
                cands = [(rid, row) for rid, row in self._replicas.items()
                         if row["model"] == model and row["healthy"]]
            if not cands:
                self.shed["no_capacity"] += 1
                raise gateway.OverloadError(
                    "no_capacity",
                    "no healthy replica for {}@{}".format(
                        model, want or "default"))
            if len(cands) == 1:
                rid, row = cands[0]
            else:
                # power of two choices by in-flight depth
                a, b = self._rng.sample(cands, 2)
                rid, row = min(
                    (a, b), key=lambda c: self._inflight.get(c[0], 0))
            self._inflight[rid] = self._inflight.get(rid, 0) + 1
            self.picks[rid] = self.picks.get(rid, 0) + 1
            return rid, row["addr"], row["version"]

    def done(self, replica_id):
        """Return a routed request's replica slot (in-flight accounting)."""
        with self._lock:
            self._inflight[replica_id] = max(
                0, self._inflight.get(replica_id, 1) - 1)

    # -- surfaces ----------------------------------------------------------

    def counters(self):
        with self._lock:
            out = {"fleet_router_shed_unknown_model":
                       self.shed["unknown_model"],
                   "fleet_router_shed_no_capacity": self.shed["no_capacity"],
                   "fleet_router_requests": sum(self.picks.values())}
            for model, n in self.admitted.items():
                out["fleet_admitted_{}".format(model)] = n
            return out

    def status(self):
        with self._lock:
            return json_safe({
                "replicas": {rid: dict(row)
                             for rid, row in self._replicas.items()},
                "picks": dict(self.picks),
                "inflight": dict(self._inflight),
                "model_inflight": dict(self._model_inflight),
                "split": {m: dict(w) for m, w in self._split.items()},
                "admitted": dict(self.admitted),
                "shed": dict(self.shed),
                "budget_per_model": self.budget_per_model})


class FleetClient(object):
    """Multi-model HA client: admission + routing through a
    :class:`FleetRouter`, transport over per-address gateway channels.

    Channels are thread-local so concurrent caller threads don't
    serialize on one socket.  A transport failure marks the replica
    unhealthy in the router and retries elsewhere — the same
    zero-lost-accepted-requests contract as ``ServingClient``, extended
    across models and versions.
    """

    def __init__(self, router, timeout=30.0, client_id=None):
        self.router = router
        self.timeout = timeout
        self.client_id = client_id
        self._tls = threading.local()
        self.failovers = 0
        self.shed = 0

    def _channel(self, addr):
        from . import gateway
        chans = getattr(self._tls, "chans", None)
        if chans is None:
            chans = self._tls.chans = {}
        chan = chans.get(addr)
        if chan is None:
            chan = chans[addr] = gateway.GatewayChannel(
                addr, timeout=self.timeout, client_id=self.client_id)
        return chan

    def _drop(self, addr):
        chans = getattr(self._tls, "chans", None)
        if chans:
            chan = chans.pop(addr, None)
            if chan is not None:
                try:
                    chan.close()
                except OSError:
                    pass

    def predict(self, model, feed, count, version=None, deadline_ms=None):
        """Route + predict.  Raises ``OverloadError`` on typed sheds
        (``unknown_model`` / ``no_capacity`` from the router, or any
        gateway-side shed); transport failures fail over."""
        from . import gateway
        with self.router.admit(model):
            last = None
            for _ in range(max(2, len(self.router.replicas(model)) + 1)):
                rid, addr, _ver = self.router.route(model, version=version)
                chan = self._channel(addr)
                try:
                    return chan.predict(feed, count, deadline_ms=deadline_ms)
                except gateway.OverloadError as e:
                    self.shed += 1
                    raise
                except (OSError, EOFError, RuntimeError) as e:
                    last = e
                    self.failovers += 1
                    self.router.set_health(rid, False)
                    self._drop(addr)
                finally:
                    self.router.done(rid)
            raise (last if last is not None
                   else RuntimeError("no replica reachable"))

    def close(self):
        chans = getattr(self._tls, "chans", None) or {}
        for addr in list(chans):
            self._drop(addr)


#: canary controller defaults — windows sized for test/CI cadence; raise
#: interval/clean_windows for production rollouts
DEFAULT_CANARY_CONFIG = {
    "interval_secs": 0.5,        # tick period
    "canary_weight": 0.1,        # traffic share while in canary
    "clean_windows": 3,          # consecutive clean ticks to promote
    "min_requests": 5,           # a window needs this many to count
    "max_err_rate": 0.05,        # SLO-violation share that burns
    "confirm_windows": 2,        # burn streak before rollback (hysteresis)
    "cooldown_secs": 5.0,        # after a promote
    "revert_cooldown_secs": 30.0,  # after a rollback — don't retry a bad v
    "swap_timeout_secs": 30.0,   # knob pushed -> replica confirms
}


class CanaryController(object):
    """Walks staging versions to live through a canary stage, or back.

    Each tick: (1) reconcile the router's version table against the
    latest heartbeat metric strings; (2) if a canary is in flight, judge
    its version-labeled window (``serving_nonfinite`` delta > 0 is an
    instant rollback; SLO err-rate above ``max_err_rate`` bumps a
    confirm streak; enough clean windows promote); (3) otherwise scan
    the registry for the newest staging version not in cooldown and
    propose it — push the ``serving_load_version`` knob at ONE replica
    of the model, wait for the heartbeat-confirmed version flip, then
    split ``canary_weight`` of traffic onto it.

    ``metrics_fn`` returns ``{node: counters}`` (the reservation server's
    ``metrics_snapshot``); ``push_knobs(knobs, executor_id=)`` is the
    KnobCoordinator push.  All stages ride :class:`Guardrails`
    (one action in flight, confirm streaks, per-model cooldown) and the
    journal, so :func:`replay_journal` re-derives every decision.
    """

    def __init__(self, registry, router, metrics_fn=None, push_knobs=None,
                 config=None, journal_path=None, clock=time.time):
        self.registry = registry
        self.router = router
        self.metrics_fn = metrics_fn or (lambda: {})
        self.push_knobs = push_knobs or (lambda knobs, executor_id=None: None)
        self.config = dict(DEFAULT_CANARY_CONFIG)
        self.config.update(config or {})
        self._clock = clock
        self._guard = Guardrails(self.config["cooldown_secs"],
                                 self.config["revert_cooldown_secs"])
        self._journal = JsonlJournal(journal_path, owner="fleet-canary")
        self._journal.write({"kind": "meta", "canary": True, "version": 1,
                             "time": self._clock(),
                             "config": json_safe(self.config)})
        self._lock = threading.Lock()
        self._seq = 0
        self._alert_flags = []   # standing version-labeled alerts observed
        self.decisions = []      # (stage, model, version) history
        self._thread = None
        self._stop = threading.Event()

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-canary")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._journal.close()

    def _run(self):
        while not self._stop.wait(self.config["interval_secs"]):
            try:
                self.tick()
            except Exception:
                logger.warning("canary tick failed", exc_info=True)

    # -- external signals --------------------------------------------------

    def observe_alert(self, alert):
        """Feed a watchtower alert; version-labeled ``slo_budget_burn`` /
        ``nonfinite`` alerts matching the in-flight canary count as an
        immediate violation window."""
        if not isinstance(alert, dict):
            return
        if alert.get("rule") not in ("slo_budget_burn", "nonfinite"):
            return
        with self._lock:
            self._alert_flags.append({
                "rule": alert.get("rule"),
                "model": alert.get("model"),
                "version": alert.get("version"),
                "executor": alert.get("executor")})
        self._journal.write({"kind": "alert", "time": self._clock(),
                             "rule": alert.get("rule"),
                             "model": alert.get("model"),
                             "version": alert.get("version"),
                             "executor": alert.get("executor")})

    # -- the control loop --------------------------------------------------

    def tick(self, now=None):
        now = self._clock() if now is None else now
        raw = self.metrics_fn() or {}
        if isinstance(raw.get("nodes"), dict):
            raw = raw["nodes"]  # reservation.Server.metrics_snapshot shape
        snapshot = {node: dict(c or {}) for node, c in raw.items()}
        self._reconcile(snapshot)
        self._journal.write({"kind": "sample", "time": now,
                             "nodes": self._sample_view(snapshot)})
        pend = self._guard.pending
        if pend is not None:
            self._advance(pend, snapshot, now)
        else:
            self._propose(now)

    @staticmethod
    def _sample_view(snapshot):
        """Journal only what replay needs: per-node model/version strings
        plus the SLO + nonfinite counters."""
        keep = ("serving_model", "serving_model_version", "serving_requests",
                "serving_slo_good", "serving_slo_total", "serving_nonfinite")
        return {node: {k: c[k] for k in keep if k in c}
                for node, c in snapshot.items() if "serving_model" in c}

    def _reconcile(self, snapshot):
        for node, c in snapshot.items():
            ver = c.get("serving_model_version")
            if ver is not None:
                self.router.note_version(node, ver)

    def _propose(self, now):
        """Scan for the newest staging version of a model not in cooldown
        and start its canary."""
        for model in self.registry.models():
            if self._guard.in_cooldown(model, now):
                continue
            staging = [e for e in self.registry.versions(model)
                       if e["status"] == "staging"]
            if not staging:
                continue
            entry = staging[-1]
            live = self.registry.default_version(model)
            replicas = self.router.replicas(model, healthy_only=True)
            if not replicas:
                continue  # nothing serving the model yet; wait
            # canary on one replica; prefer one running the live version
            target = next((rid for rid, row in sorted(replicas.items())
                           if live is None or row["version"] == live),
                          sorted(replicas)[0])
            prev_version = replicas[target]["version"]
            self._seq += 1
            token = "canary-{}-{}".format(entry["version"], self._seq)
            rec = {"kind": "stage", "stage": "proposed", "time": now,
                   "model": model, "version": entry["version"],
                   "prev_version": prev_version, "replica": target,
                   "token": token}
            self._journal.write(rec)
            self._guard.begin({
                "model": model, "version": entry["version"],
                "prev_version": prev_version, "replica": target,
                "token": token, "state": "swapping", "since": now,
                "clean": 0, "baseline": None})
            self.push_knobs(
                {"serving_load_version": {
                    "model": model, "version": entry["version"],
                    "export_dir": entry["export_dir"],
                    "token": token}},
                executor_id=target)
            logger.info("canary: proposed %s@%s on replica %s (prev %s)",
                        model, entry["version"], target, prev_version)
            return

    def _advance(self, pend, snapshot, now):
        model, version = pend["model"], pend["version"]
        node = snapshot.get(pend["replica"], {})
        if pend["state"] == "swapping":
            if str(node.get("serving_model_version")) == version:
                self.router.note_version(pend["replica"], version)
                live = self.registry.default_version(model)
                weight = self.config["canary_weight"]
                split = {version: weight}
                if live:
                    split[live] = 1.0 - weight
                self.router.set_split(model, split)
                self.registry.set_status(model, version, "canary")
                pend["state"] = "watching"
                pend["baseline"] = self._counters_of(node)
                self._journal.write({
                    "kind": "stage", "stage": "applied", "time": now,
                    "model": model, "version": version,
                    "replica": pend["replica"], "split": json_safe(split)})
            elif now - pend["since"] > self.config["swap_timeout_secs"]:
                self._rollback(pend, now, reason="swap_timeout")
            return
        # watching: judge the canary replica's window
        cur = self._counters_of(node)
        base = pend["baseline"] or cur
        pend["baseline"] = cur
        verdict = judge_window(base, cur, self.config,
                               alerts=self._drain_alerts(model, version))
        self._journal.write({"kind": "stage", "stage": "effect", "time": now,
                            "model": model, "version": version,
                            "replica": pend["replica"],
                            "window": json_safe(verdict)})
        if verdict["verdict"] == "violation":
            if (verdict.get("instant")
                    or self._guard.bump_streak(model)
                    >= self.config["confirm_windows"]):
                self._rollback(pend, now, reason=verdict["reason"])
            return
        self._guard.clear_streak(model)
        if verdict["verdict"] == "clean":
            pend["clean"] += 1
            if pend["clean"] >= self.config["clean_windows"]:
                self._promote(pend, now)

    @staticmethod
    def _counters_of(node):
        return {k: float(node.get(k, 0) or 0)
                for k in ("serving_slo_good", "serving_slo_total",
                          "serving_nonfinite")}

    def _drain_alerts(self, model, version):
        with self._lock:
            flags, self._alert_flags = self._alert_flags, []
        return [a for a in flags
                if (a.get("model") in (None, model))
                and (a.get("version") in (None, version))]

    def _promote(self, pend, now):
        model, version = pend["model"], pend["version"]
        entry = self.registry.resolve(model, version)
        # flip every other replica of the model, then the registry default
        for rid in sorted(self.router.replicas(model)):
            if rid == pend["replica"]:
                continue
            self._seq += 1
            self.push_knobs(
                {"serving_load_version": {
                    "model": model, "version": version,
                    "export_dir": entry["export_dir"],
                    "token": "promote-{}-{}".format(version, self._seq)}},
                executor_id=rid)
        self.registry.set_status(model, version, "live")
        self.router.set_split(model, None)
        self._journal.write({"kind": "stage", "stage": "kept", "time": now,
                            "model": model, "version": version,
                            "clean_windows": pend["clean"]})
        self.decisions.append(("kept", model, version))
        self._guard.settle()
        self._guard.start_cooldown(model, now)
        self._guard.clear_streak(model)
        logger.info("canary: promoted %s@%s to live", model, version)

    def _rollback(self, pend, now, reason):
        model, version = pend["model"], pend["version"]
        prev = pend["prev_version"]
        try:
            entry = self.registry.resolve(model, prev)
        except KeyError:
            entry = None
        if entry is not None:
            self._seq += 1
            self.push_knobs(
                {"serving_load_version": {
                    "model": model, "version": prev,
                    "export_dir": entry["export_dir"],
                    "token": "rollback-{}-{}".format(prev, self._seq)}},
                executor_id=pend["replica"])
        self.router.set_split(model, None)
        try:
            self.registry.set_status(model, version, "retired", reason=reason)
        except KeyError:
            pass
        self._journal.write({"kind": "stage", "stage": "reverted",
                            "time": now, "model": model, "version": version,
                            "reason": reason, "rolled_back_to": prev})
        self.decisions.append(("reverted", model, version))
        self._guard.settle()
        self._guard.start_cooldown(model, now, reverted=True)
        self._guard.clear_streak(model)
        logger.warning("canary: rolled back %s@%s (%s) to %s", model,
                       version, reason, prev)

    def status(self):
        now = self._clock()
        return json_safe({
            "pending": dict(self._guard.pending or {}) or None,
            "cooldowns": self._guard.cooldowns(now),
            "decisions": [{"stage": s, "model": m, "version": v}
                          for s, m, v in self.decisions]})


def judge_window(base, cur, config, alerts=()):
    """Pure canary-window verdict off two counter samples — the single
    decision function both the live controller and offline replay run,
    so journal replay cannot drift from production behavior.

    Returns ``{"verdict": "clean"|"violation"|"insufficient", ...}``.
    A nonfinite delta or a matching standing alert is an *instant*
    violation (no streak); an err-rate above ``max_err_rate`` with at
    least ``min_requests`` in the window is a streaked violation.
    """
    nonfinite = cur.get("serving_nonfinite", 0) - base.get(
        "serving_nonfinite", 0)
    total = cur.get("serving_slo_total", 0) - base.get("serving_slo_total", 0)
    good = cur.get("serving_slo_good", 0) - base.get("serving_slo_good", 0)
    if nonfinite > 0:
        return {"verdict": "violation", "instant": True,
                "reason": "nonfinite", "nonfinite": nonfinite}
    for a in alerts:
        if a.get("rule") == "nonfinite":
            return {"verdict": "violation", "instant": True,
                    "reason": "nonfinite_alert", "alert": a}
    if total < config["min_requests"]:
        return {"verdict": "insufficient", "requests": total}
    err_rate = max(0.0, (total - good) / total) if total else 0.0
    if err_rate > config["max_err_rate"]:
        return {"verdict": "violation", "instant": False,
                "reason": "err_rate", "err_rate": round(err_rate, 4),
                "requests": total}
    for a in alerts:
        return {"verdict": "violation", "instant": False,
                "reason": "burn_alert", "alert": a}
    return {"verdict": "clean", "err_rate": round(err_rate, 4),
            "requests": total}


# -- train-to-serve handoff -------------------------------------------------

def publish_trained(spec, params, step):
    """Publish a training run's final params to a registry as ``staging``.

    ``spec`` (the ``fit_supervised(publish=...)`` value)::

        {"registry": ModelRegistry-or-root-path, "model": name,
         "version": str (default "step-<N>"), "model_name": descriptor name,
         "model_config": {...}, "input_signature": {...},
         "warm_dir": path or None}

    Params are finiteness-validated BEFORE export (a poisoned checkpoint
    must never enter the fleet — the quarantine discipline of
    ``restore_latest_valid`` applied at the publish boundary), exported
    with ``checkpoint.export_model`` into the registry layout, and
    journaled as a staging version for the canary controller to walk to
    live.  Returns the registry entry.
    """
    import jax

    from . import checkpoint

    model = _check_name("model", spec["model"])
    registry = spec["registry"]
    if not isinstance(registry, ModelRegistry):
        registry = ModelRegistry(registry)
    version = _check_name("version",
                          spec.get("version") or "step-{}".format(int(step)))
    host_params = jax.device_get(params)
    bad = checkpoint._nonfinite_leaves(host_params)
    if bad:
        raise ValueError(
            "refusing to publish {}@{}: nonfinite leaves {}".format(
                model, version, bad[:4]))
    export_dir = spec.get("export_dir") or os.path.join(
        registry.root, model, version)
    checkpoint.export_model(
        export_dir, host_params,
        spec.get("model_name") or model,
        model_config=spec.get("model_config"),
        input_signature=spec.get("input_signature"),
        model=spec.get("flax_model"))
    return registry.publish(model, version, export_dir,
                            model_config=spec.get("model_config"),
                            warm_dir=spec.get("warm_dir"),
                            status=spec.get("status", "staging"))


# -- offline replay ---------------------------------------------------------

def replay_journal(records, config=None):
    """Re-derive the canary decision stream from a journal.

    ``records`` is a path or a record list.  The replay runs the SAME
    :func:`judge_window` math the live controller ran, over the journaled
    per-tick samples, from each ``proposed``/``applied`` stage forward —
    so a promotion or rollback in the journal is *re-derivable*, not just
    recorded.  Returns::

        {"decisions": [...derived...], "journaled": [...from journal...],
         "matches": bool, "config": {...}}
    """
    from .watchtower import read_journal

    if isinstance(records, str):
        records = read_journal(records)
    cfg = dict(DEFAULT_CANARY_CONFIG)
    for rec in records:
        if rec.get("kind") == "meta" and rec.get("canary"):
            cfg.update(rec.get("config") or {})
    cfg.update(config or {})
    derived, journaled = [], []
    pend = None
    streak = 0
    alerts = []
    last_nodes = {}
    for rec in records:
        kind = rec.get("kind")
        if kind == "alert":
            alerts.append(rec)
        elif kind == "stage":
            stage = rec.get("stage")
            if stage in ("kept", "reverted"):
                journaled.append((stage, rec["model"], rec["version"]))
            if stage == "proposed":
                pend = {"model": rec["model"], "version": rec["version"],
                        "replica": rec["replica"], "state": "swapping",
                        "clean": 0, "baseline": None}
                streak = 0
            elif stage == "applied" and pend is not None:
                # the live controller seeded its baseline from the tick
                # that confirmed the swap — that tick's sample record was
                # written just before this stage record
                pend["state"] = "watching"
                node = last_nodes.get(pend["replica"])
                if node is not None:
                    pend["baseline"] = CanaryController._counters_of(node)
        elif kind == "sample":
            last_nodes = rec.get("nodes") or {}
            if pend is None:
                continue
            node = last_nodes.get(pend["replica"])
            if node is None or pend["state"] != "watching":
                continue
            cur = CanaryController._counters_of(node)
            if pend["baseline"] is None:
                pend["baseline"] = cur
                continue
            matched = [a for a in alerts
                       if a.get("model") in (None, pend["model"])
                       and a.get("version") in (None, pend["version"])]
            alerts = []
            verdict = judge_window(pend["baseline"], cur, cfg,
                                   alerts=matched)
            pend["baseline"] = cur
            if verdict["verdict"] == "violation":
                streak += 1
                if verdict.get("instant") or streak >= cfg["confirm_windows"]:
                    derived.append(("reverted", pend["model"],
                                    pend["version"]))
                    pend = None
                continue
            streak = 0
            if verdict["verdict"] == "clean":
                pend["clean"] += 1
                if pend["clean"] >= cfg["clean_windows"]:
                    derived.append(("kept", pend["model"], pend["version"]))
                    pend = None
    return {"decisions": derived, "journaled": journaled,
            "matches": derived == journaled, "config": cfg}
