"""CLI entry for a data-service feed worker process.

Runs one :class:`~tensorflowonspark_tpu.dataservice.FeedWorker` until
SIGTERM / Ctrl-C, then deregisters cleanly (``BYE``).  Chaos specs ride the
usual ``TFOS_FAULT_SPEC`` environment variable (e.g.
``{"kill_after_splits": 2}`` for the CI worker-kill gate).

Usage::

    python -m tensorflowonspark_tpu.dataservice_worker \\
        --dispatcher HOST:PORT [--reader jsonl|tfrecord] [--host H] \\
        [--port P] [--worker-id ID] [--heartbeat SECS] [--process-pool] \\
        [--cache-bytes N] [--cache-spill-dir DIR] [--no-cache-advertise]

The standalone dispatcher lives in
:mod:`~tensorflowonspark_tpu.dataservice_dispatcher` (journal + affinity
knobs are dispatcher-side).
"""

import argparse
import logging
import signal
import sys
import threading


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="tensorflowonspark_tpu data-service feed worker")
    parser.add_argument("--dispatcher", required=True,
                        help="dispatcher address, host:port")
    parser.add_argument("--reader", choices=("jsonl", "tfrecord"),
                        default="tfrecord",
                        help="row reader for split files (default: tfrecord)")
    parser.add_argument("--host", default="127.0.0.1",
                        help="data-port bind/advertise host")
    parser.add_argument("--port", type=int, default=0,
                        help="data port (default: ephemeral)")
    parser.add_argument("--worker-id", default=None,
                        help="worker identity (default: generated)")
    parser.add_argument("--heartbeat", type=float, default=1.0,
                        help="heartbeat interval seconds")
    parser.add_argument("--process-pool", action="store_true",
                        help="read splits with ProcessPoolFeed")
    parser.add_argument("--cache-bytes", type=int, default=None,
                        help="chunk-cache byte budget (default: "
                             "TFOS_DS_CACHE_BYTES env, 0/unset disables); "
                             "a starting value only — the driver autopilot "
                             "can retune it live over the dispatcher "
                             "heartbeat (dataservice_cache_budget knob)")
    parser.add_argument("--cache-spill-dir", default=None,
                        help="spill LRU-evicted cache entries to this dir")
    parser.add_argument("--no-cache-advertise", dest="advertise_cache",
                        action="store_false", default=None,
                        help="do not advertise cached splits to the "
                             "dispatcher (disables cache-affinity "
                             "scheduling for this worker; default: "
                             "TFOS_DS_ADVERTISE env, on)")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")

    from tensorflowonspark_tpu import data, dataservice, telemetry

    # Standalone workers opt into telemetry via TFOS_TELEMETRY /
    # TFOS_TELEMETRY_DIR (no cluster_meta hop reaches a CLI process), and
    # get the SIGUSR1 flight recorder either way the cluster shells do:
    # a hung worker can then be asked for stacks (`kill -USR1 <pid>`)
    # instead of diagnosed post-mortem.
    tracer = telemetry.configure_from_meta({})
    telemetry.install_sigusr1()

    row_reader = (data.jsonl_rows if args.reader == "jsonl"
                  else data.tfrecord_rows)
    worker = dataservice.FeedWorker(
        args.dispatcher, row_reader=row_reader, host=args.host,
        port=args.port, worker_id=args.worker_id,
        heartbeat_interval=args.heartbeat,
        use_process_pool=args.process_pool,
        cache_bytes=args.cache_bytes,
        cache_spill_dir=args.cache_spill_dir,
        advertise_cache=args.advertise_cache)
    worker.start()
    print("worker {} ready on {}:{}".format(worker.worker_id, worker.host,
                                            worker.port), flush=True)

    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda signum, frame: done.set())
    try:
        done.wait()
    except KeyboardInterrupt:
        pass
    worker.stop()
    tracer.flush()
    return 0


if __name__ == "__main__":
    sys.exit(main())
