"""Schema-string parser (reference ``SimpleTypeParser.scala``).

The reference's JVM inference CLI accepts a Spark ``simpleString`` schema
hint — ``struct<name:type,...>`` with scalar and 1-D array columns
(reference ``SimpleTypeParser.scala:28-64``, used via ``--schema_hint``,
``Inference.scala:30-43``, ``DFUtil.scala:75``).  This module parses the
same grammar into the framework's dfutil schema dict
(``{col: int64|float32|string|binary|array<...>}``).
"""

import re

# Spark simpleString base types -> dfutil types (reference grammar accepts
# the SQL names; DFUtilTest.scala documents the lossy long/float collapse).
_BASE_TYPES = {
    "tinyint": "int64",
    "smallint": "int64",
    "int": "int64",
    "integer": "int64",
    "bigint": "int64",
    "long": "int64",
    "boolean": "int64",
    "float": "float32",
    "double": "float32",
    "string": "string",
    "binary": "binary",
}

_NAME_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


class SchemaParseError(ValueError):
    pass


def _parse_type(text):
    text = text.strip().lower()
    if text.startswith("array<") and text.endswith(">"):
        inner = _parse_type(text[len("array<"):-1])
        if inner.startswith("array<"):
            raise SchemaParseError(
                "nested arrays are not supported (reference grammar is "
                "1-D arrays only): {!r}".format(text))
        return "array<{}>".format(inner)
    if text not in _BASE_TYPES:
        raise SchemaParseError(
            "unknown type {!r}; expected one of {} or array<...>".format(
                text, sorted(set(_BASE_TYPES))))
    return _BASE_TYPES[text]


def _split_fields(body):
    """Split ``a:int,b:array<float>`` on commas not nested in ``<>``."""
    fields, depth, start = [], 0, 0
    for i, ch in enumerate(body):
        if ch == "<":
            depth += 1
        elif ch == ">":
            depth -= 1
            if depth < 0:
                raise SchemaParseError("unbalanced '>' in {!r}".format(body))
        elif ch == "," and depth == 0:
            fields.append(body[start:i])
            start = i + 1
    if depth != 0:
        raise SchemaParseError("unbalanced '<' in {!r}".format(body))
    fields.append(body[start:])
    return fields


def parse(simple_string):
    """``struct<name:type,...>`` -> ``{name: dfutil_type}`` (ordered).

    Reference ``SimpleTypeParser.parse`` (``SimpleTypeParser.scala:28-31``);
    raises :class:`SchemaParseError` on malformed input.
    """
    text = simple_string.strip()
    if not (text.lower().startswith("struct<") and text.endswith(">")):
        raise SchemaParseError(
            "schema must look like struct<name:type,...>, got {!r}".format(
                simple_string))
    body = text[len("struct<"):-1].strip()
    if not body:
        return {}
    schema = {}
    for field in _split_fields(body):
        if ":" not in field:
            raise SchemaParseError("field {!r} is missing ':'".format(field))
        name, _, coltype = field.partition(":")
        name = name.strip()
        if not _NAME_RE.match(name):
            raise SchemaParseError("bad column name {!r}".format(name))
        if name in schema:
            raise SchemaParseError("duplicate column {!r}".format(name))
        schema[name] = _parse_type(coltype)
    return schema
