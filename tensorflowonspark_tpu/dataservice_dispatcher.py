"""CLI entry for a standalone data-service dispatcher process.

Runs one :class:`~tensorflowonspark_tpu.dataservice.DispatcherServer`
until SIGTERM / Ctrl-C.  With ``--journal-dir`` the split ledger is
journaled (JSONL mutations + periodic snapshots) and a restarted
dispatcher — same ``--port``, same ``--journal-dir`` — recovers every
job's ledger before accepting connections, so SIGKILLing this process is
survivable: workers re-register off the heartbeat ``reregister`` hint,
consumers reconnect lazily, and in-flight splits resume exactly-once.

Usage::

    python -m tensorflowonspark_tpu.dataservice_dispatcher \\
        [--host H] [--port P] [--heartbeat SECS] [--misses N] \\
        [--journal-dir DIR] [--snapshot-every N] \\
        [--journal-keep N | --journal-keep-bytes N] \\
        [--affinity | --no-affinity]

Env fallbacks (flags win): ``TFOS_DS_JOURNAL_DIR``,
``TFOS_DS_SNAPSHOT_EVERY``, ``TFOS_DS_JOURNAL_KEEP``,
``TFOS_DS_JOURNAL_KEEP_BYTES``, ``TFOS_DS_AFFINITY`` — the same shape as
the worker CLI's ``TFOS_DS_CACHE_BYTES``.
"""

import argparse
import logging
import signal
import sys
import threading


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="tensorflowonspark_tpu data-service dispatcher")
    parser.add_argument("--host", default=None,
                        help="advertise host (default: auto-detected)")
    parser.add_argument("--port", type=int, default=0,
                        help="listen port (default: ephemeral; pin it so a "
                             "restarted dispatcher keeps its address)")
    parser.add_argument("--heartbeat", type=float, default=1.0,
                        help="worker heartbeat interval seconds")
    parser.add_argument("--misses", type=int, default=3,
                        help="missed heartbeats before fencing")
    parser.add_argument("--journal-dir", default=None,
                        help="journal ledger mutations under this dir "
                             "(default: TFOS_DS_JOURNAL_DIR env; unset "
                             "disables durability)")
    parser.add_argument("--snapshot-every", type=int, default=None,
                        help="journal records between full snapshots "
                             "(default: TFOS_DS_SNAPSHOT_EVERY env, 512)")
    parser.add_argument("--journal-keep", type=int, default=None,
                        help="snapshot generations kept after compaction "
                             "(default: TFOS_DS_JOURNAL_KEEP env, 2)")
    parser.add_argument("--journal-keep-bytes", type=int, default=None,
                        help="byte budget for retired generations instead "
                             "of a count; the newest generation is always "
                             "kept (default: TFOS_DS_JOURNAL_KEEP_BYTES "
                             "env, 0 = use --journal-keep)")
    parser.add_argument("--affinity", dest="affinity", action="store_true",
                        default=None,
                        help="cache-affinity DYNAMIC scheduling (default: "
                             "TFOS_DS_AFFINITY env, on)")
    parser.add_argument("--no-affinity", dest="affinity",
                        action="store_false",
                        help="plain FCFS DYNAMIC scheduling")
    parser.add_argument("--standby", action="store_true",
                        help="arm as a warm standby: tail the primary's "
                             "beacon in --journal-dir and promote when it "
                             "goes silent past --takeover-after")
    parser.add_argument("--takeover-after", type=float, default=2.0,
                        help="beacon silence (seconds) before a standby "
                             "promotes itself")
    parser.add_argument("--poll", type=float, default=0.2,
                        help="standby beacon poll interval seconds")
    parser.add_argument("--takeover-grace", type=float, default=None,
                        help="seconds after a recovery during which worker/"
                             "consumer fencing is suppressed (default: "
                             "heartbeat × misses, at least 2s)")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")

    from tensorflowonspark_tpu import dataservice, fault, standby, telemetry

    tracer = telemetry.configure_from_meta({})
    telemetry.install_sigusr1()

    if args.standby and not args.journal_dir:
        parser.error("--standby requires --journal-dir (the standby tails "
                     "the primary's beacon and recovers its ledger there)")

    def build():
        return dataservice.DispatcherServer(
            heartbeat_interval=args.heartbeat, heartbeat_misses=args.misses,
            host=args.host, port=args.port, journal_dir=args.journal_dir,
            snapshot_every=args.snapshot_every, affinity=args.affinity,
            journal_keep=args.journal_keep,
            journal_keep_bytes=args.journal_keep_bytes,
            takeover_grace=args.takeover_grace)

    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda signum, frame: done.set())

    watcher = None
    dispatcher = None
    if args.standby:
        def announce(promoted, addr):
            print("dispatcher promoted on {}:{} epoch={}".format(
                addr[0], addr[1], promoted.fencing_epoch), flush=True)
            fault.from_env().arm_coordinator_kill("dispatcher")

        watcher = standby.WarmStandby(
            build, args.journal_dir, takeover_after=args.takeover_after,
            poll_interval=args.poll, on_promote=announce,
            name="dispatcher").start()
        print("dispatcher standby armed on {} (takeover after {:.1f}s)"
              .format(args.journal_dir, args.takeover_after), flush=True)
    else:
        dispatcher = build()
        host, port = dispatcher.start()
        print("dispatcher ready on {}:{}".format(host, port), flush=True)
        # Chaos scripting: kill_coordinator_after_secs in TFOS_FAULT_SPEC
        # SIGKILLs this process on schedule, like node faults kill nodes.
        fault.from_env().arm_coordinator_kill("dispatcher")

    try:
        done.wait()
    except KeyboardInterrupt:
        pass
    if watcher is not None:
        watcher.stop()
        if watcher.server is not None:
            watcher.server.stop()
    if dispatcher is not None:
        dispatcher.stop()
    tracer.flush()
    return 0


if __name__ == "__main__":
    sys.exit(main())
