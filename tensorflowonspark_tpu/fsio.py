"""Local + remote filesystem access for the data path.

The reference trains from HDFS: executors read TFRecord shards through
Hadoop's filesystem layer (classpath plumbing ``TFSparkNode.py:191-197``,
TFRecord loads ``dfutil.py:44-81``, tf.data file reads
``examples/mnist/keras/mnist_tf.py:23-27``).  The TPU-first deployment
equivalent is an object store — on a v5e pod the training shards live in
GCS — so every file touch in the data path (TFRecord read/write, shard
listing, raw byte streams) routes through this module:

- **local paths stay on the stdlib fast path** (``open``/``glob``/``os``)
  — zero new overhead for the common case;
- **URLs with a scheme** (``gs://``, ``hdfs://``, ``s3://``, ``memory://``,
  …) go through ``fsspec``, which resolves the protocol to an installed
  backend (``gcsfs`` for GCS, ``pyarrow``/``fsspec[hdfs]`` for HDFS).
  ``fsspec`` itself is a hard dependency of this module's remote branch
  only; a purely-local workload never imports it.

``file://`` URLs are normalized to plain local paths.
"""

import glob as _glob
import os

__all__ = ["is_remote", "open_file", "glob", "isdir", "exists", "makedirs",
           "join", "strip_file_scheme"]


def strip_file_scheme(path):
    """``file:///x`` / ``file:/x`` -> ``/x`` (local paths with an explicit
    scheme take the stdlib fast path like any other local path)."""
    if path.startswith("file://"):
        return path[len("file://"):] or "/"
    if path.startswith("file:"):
        return path[len("file:"):]
    return path


def _scheme(path):
    """URL scheme of ``path``, or None for plain local paths.  A Windows
    drive letter (``C:\\...``) is not a scheme; neither is a path with no
    ``://``."""
    head, sep, _ = path.partition("://")
    if not sep or not head or "/" in head:
        return None
    return head


def is_remote(path):
    """True when ``path`` needs an fsspec backend (any scheme but file)."""
    return _scheme(strip_file_scheme(path)) is not None


def _fs(path):
    import fsspec

    return fsspec.core.url_to_fs(path)


def open_file(path, mode="rb", **kwargs):
    """Open ``path`` for streaming IO: builtin ``open`` locally, an fsspec
    buffered file for remote URLs.  Both return context-manager file
    objects with the standard read/write/seek surface."""
    path = strip_file_scheme(path)
    if not is_remote(path):
        return open(path, mode, **kwargs)
    import fsspec

    return fsspec.open(path, mode, **kwargs).open()


def glob(pattern):
    """Sorted matches for ``pattern``; remote results keep their scheme."""
    pattern = strip_file_scheme(pattern)
    if not is_remote(pattern):
        return sorted(_glob.glob(pattern))
    fs, rel = _fs(pattern)
    return sorted(fs.unstrip_protocol(p) for p in fs.glob(rel))


def isdir(path):
    path = strip_file_scheme(path)
    if not is_remote(path):
        return os.path.isdir(path)
    fs, rel = _fs(path)
    return fs.isdir(rel)


def exists(path):
    path = strip_file_scheme(path)
    if not is_remote(path):
        return os.path.exists(path)
    fs, rel = _fs(path)
    return fs.exists(rel)


def makedirs(path, exist_ok=True):
    """mkdir -p; for object stores this is a (cheap) no-op placeholder."""
    path = strip_file_scheme(path)
    if not is_remote(path):
        os.makedirs(path, exist_ok=exist_ok)
        return
    fs, rel = _fs(path)
    fs.makedirs(rel, exist_ok=exist_ok)


def join(base, *parts):
    """Path join that preserves URL schemes (``os.path.join`` would not
    mangle them on posix, but this keeps intent explicit and wins on
    Windows)."""
    if is_remote(base):
        pieces = [base.rstrip("/")]
        pieces.extend(p.strip("/") for p in parts)
        return "/".join(pieces)
    return os.path.join(base, *parts)
