"""Shared guardrail + journal primitives for the control planes.

The autopilot (PR 14, knob plane) and the remediator (topology plane)
run the same action discipline — confirm-streak hysteresis, per-key
cooldown, one action in flight, a flush-per-write JSONL journal over the
``proposed -> applied -> effect -> kept/reverted`` stage vocabulary.
This module is that discipline extracted once so the two controllers
cannot drift: :class:`Guardrails` owns the gating state,
:class:`JsonlJournal` owns the crash-safe append stream, and
:data:`STAGES` is the shared lifecycle vocabulary.
"""

import json
import logging
import os
import threading

from .watchtower import json_safe

logger = logging.getLogger(__name__)

#: action lifecycle stages, in order — the journal's ``stage`` vocabulary
STAGES = ("proposed", "applied", "effect", "kept", "reverted")


class JsonlJournal(object):
    """Append-only flush-per-write JSONL stream (crash-safe: every record
    is flushed before the write returns, so a driver crash mid-run loses
    at most the record being written).  ``path=None`` disables — every
    write becomes a no-op, so callers never branch.

    Thread-safe; the file is opened lazily on the first write (parent
    directory created), so constructing one is free.
    """

    def __init__(self, path, owner="journal"):
        self.path = path
        self._owner = owner
        self._fh = None
        self._lock = threading.Lock()

    def write(self, record):
        """Append one record (``json_safe``-coerced).  Failures are logged,
        never raised — journaling must not take the run down."""
        if self.path is None:
            return
        with self._lock:
            try:
                if self._fh is None:
                    parent = os.path.dirname(os.path.abspath(self.path))
                    os.makedirs(parent, exist_ok=True)
                    self._fh = open(self.path, "a")
                self._fh.write(json.dumps(json_safe(record), default=str)
                               + "\n")
                self._fh.flush()  # must survive a driver crash mid-run
            except Exception:
                logger.warning("%s journal write failed", self._owner,
                               exc_info=True)

    def close(self):
        """Close the stream (idempotent); later writes reopen it."""
        with self._lock:
            fh, self._fh = self._fh, None
            if fh is not None:
                try:
                    fh.close()
                except OSError:
                    pass


class Guardrails(object):
    """The action-gating state machine both controllers share:

    - **confirm streak** — ``bump_streak``/``clear_streak`` count the
      consecutive firing ticks per key; a proposal is minted only once
      the streak reaches the caller's ``confirm_ticks`` (hysteresis — one
      noisy window never triggers an action);
    - **per-key cooldown** — after an action settles the key is frozen
      (``cooldown_secs``; ``revert_cooldown_secs`` after a revert so an
      action that just hurt the run is not retried while conditions
      still match);
    - **one action in flight** — :attr:`pending` holds the single applied
      action awaiting its settle window; callers must not propose while
      it is set, so effects stay attributable.

    Not internally locked: callers serialize ticks (both controllers run
    a single control thread and take their own lock around state reads).
    """

    def __init__(self, cooldown_secs, revert_cooldown_secs=None):
        self.cooldown_secs = cooldown_secs
        self.revert_cooldown_secs = (cooldown_secs
                                     if revert_cooldown_secs is None
                                     else revert_cooldown_secs)
        self._cooldown_until = {}
        self._streak = {}
        self.pending = None

    # -- cooldown ----------------------------------------------------------

    def in_cooldown(self, key, now):
        return now < self._cooldown_until.get(key, 0.0)

    def start_cooldown(self, key, now, reverted=False):
        secs = self.revert_cooldown_secs if reverted else self.cooldown_secs
        self._cooldown_until[key] = now + secs

    def cooldowns(self, now):
        """Remaining cooldown per key (status surfaces), expired dropped."""
        return {k: round(until - now, 2)
                for k, until in self._cooldown_until.items() if until > now}

    # -- confirm streak ----------------------------------------------------

    def bump_streak(self, key):
        streak = self._streak.get(key, 0) + 1
        self._streak[key] = streak
        return streak

    def clear_streak(self, key):
        self._streak[key] = 0

    def streak(self, key):
        return self._streak.get(key, 0)

    # -- one action in flight ----------------------------------------------

    def begin(self, record):
        """Latch the one in-flight action (an ``applied`` record dict)."""
        self.pending = record

    def settle(self):
        """Release the in-flight slot; returns the settled record."""
        pend, self.pending = self.pending, None
        return pend
