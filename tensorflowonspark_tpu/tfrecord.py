"""TFRecord codec: read/write TFRecord files with masked-CRC32C framing.

First-party replacement for the reference's bundled Hadoop jar (reference
``dfutil.py:39-41`` and ``DFUtil.scala:189-192`` delegate TFRecord framing
to Java ``TFRecordFileInput/OutputFormat`` from
``lib/tensorflow-hadoop-1.0-SNAPSHOT.jar``; its wire format is
length + masked crc32c(length) + payload + masked crc32c(payload)).

Two interchangeable engines:

- the C++ library (``native/tfrecord.cc``) via ctypes — the fast path for
  bulk host-side ingestion;
- a pure-Python fallback (struct + table-driven crc32c) used when no
  toolchain is available.  Same files, bit-identical output.
"""

import ctypes
import logging
import struct

from tensorflowonspark_tpu import fsio, native

logger = logging.getLogger(__name__)

_MASK_DELTA = 0xA282EAD8


def _make_crc_table():
    poly = 0x82F63B78
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ (poly if crc & 1 else 0)
        table.append(crc)
    return table


_CRC_TABLE = _make_crc_table()


def _crc32c_py(data):
    crc = 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def _lib():
    lib = native.load("tfrecord")
    if lib is not None and not getattr(lib, "_tfr_ready", False):
        lib.tfr_crc32c.restype = ctypes.c_uint32
        lib.tfr_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.tfr_masked_crc32c.restype = ctypes.c_uint32
        lib.tfr_masked_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.tfr_writer_open.restype = ctypes.c_void_p
        lib.tfr_writer_open.argtypes = [ctypes.c_char_p]
        lib.tfr_write.restype = ctypes.c_int
        lib.tfr_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_uint64]
        lib.tfr_writer_flush.restype = ctypes.c_int
        lib.tfr_writer_flush.argtypes = [ctypes.c_void_p]
        lib.tfr_writer_close.restype = ctypes.c_int
        lib.tfr_writer_close.argtypes = [ctypes.c_void_p]
        lib.tfr_reader_open.restype = ctypes.c_void_p
        lib.tfr_reader_open.argtypes = [ctypes.c_char_p]
        lib.tfr_read_next.restype = ctypes.c_int64
        lib.tfr_read_next.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
        lib.tfr_reader_close.restype = ctypes.c_int
        lib.tfr_reader_close.argtypes = [ctypes.c_void_p]
        lib._tfr_ready = True
    return lib


def crc32c(data):
    lib = _lib()
    if lib is not None:
        return lib.tfr_crc32c(bytes(data), len(data))
    return _crc32c_py(data)


def masked_crc32c(data):
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + _MASK_DELTA) & 0xFFFFFFFF


class TFRecordWriter(object):
    """Writes TFRecord files (C++ engine when available)."""

    def __init__(self, path, use_native=True):
        path = fsio.strip_file_scheme(path)
        self.path = path
        self._handle = None
        self._file = None
        # the C++ engine does its own fopen: local paths only; remote URLs
        # (gs:// etc.) stream through fsspec via the python framing path
        lib = (_lib() if use_native and not fsio.is_remote(path) else None)
        if lib is not None:
            self._lib = lib
            self._handle = lib.tfr_writer_open(path.encode())
            if not self._handle:
                raise IOError("cannot open {} for writing".format(path))
        else:
            self._lib = None
            self._file = fsio.open_file(path, "wb")

    def write(self, record):
        record = bytes(record)
        if self._handle is not None:
            if self._lib.tfr_write(self._handle, record, len(record)):
                raise IOError("write failed on {}".format(self.path))
        else:
            header = struct.pack("<Q", len(record))
            self._file.write(header)
            self._file.write(struct.pack("<I", masked_crc32c(header)))
            self._file.write(record)
            self._file.write(struct.pack("<I", masked_crc32c(record)))

    def flush(self):
        if self._handle is not None:
            if self._lib.tfr_writer_flush(self._handle):
                raise IOError("flush failed on {}".format(self.path))
        else:
            self._file.flush()

    def close(self):
        if self._handle is not None:
            handle, self._handle = self._handle, None
            if self._lib.tfr_writer_close(handle):
                # fclose failure = buffered tail never hit disk (e.g. ENOSPC)
                raise IOError("close failed on {}".format(self.path))
        elif self._file is not None:
            f, self._file = self._file, None
            f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def tfrecord_iterator(path, use_native=True, verify_crc=True):
    """Yield raw record bytes from a TFRecord file, verifying CRCs.

    Local files prefer the C++ engine; remote URLs (``gs://``, ``hdfs://``,
    ``memory://``, ...) stream through :mod:`fsio`'s fsspec branch with the
    same framing checks.

    ``verify_crc=False`` skips both CRC checks (framing lengths still
    guard against truncation) — for hot read paths over data this process
    tree wrote and verified at write time, e.g. the pre-decoded ImageNet
    rows, where the masked-crc pass costs more than the entire record
    parse (measured 0.25 ms vs 0.05 ms on 196 KB rows, docs/PERF.md
    round 5).  The native engine always verifies; skipping routes through
    the python framing loop, which is FASTER than native-with-crc for
    large records (one syscall-sized read per field, no per-byte work)."""
    path = fsio.strip_file_scheme(path)
    lib = (_lib() if use_native and verify_crc
           and not fsio.is_remote(path) else None)
    if lib is not None:
        handle = lib.tfr_reader_open(path.encode())
        if not handle:
            raise IOError("cannot open {} for reading".format(path))
        try:
            out = ctypes.POINTER(ctypes.c_uint8)()
            while True:
                n = lib.tfr_read_next(handle, ctypes.byref(out))
                if n == -1:
                    return
                if n < 0:
                    raise IOError("corrupt TFRecord in {}".format(path))
                yield ctypes.string_at(out, n)
        finally:
            lib.tfr_reader_close(handle)
    else:
        with fsio.open_file(path, "rb") as f:
            while True:
                header = f.read(8)
                if not header:
                    return
                if len(header) != 8:
                    raise IOError("truncated TFRecord header in {}".format(path))
                (length,) = struct.unpack("<Q", header)
                crc_bytes = f.read(4)
                if len(crc_bytes) != 4:
                    raise IOError("truncated TFRecord header in {}".format(path))
                if verify_crc:
                    (len_crc,) = struct.unpack("<I", crc_bytes)
                    if masked_crc32c(header) != len_crc:
                        raise IOError(
                            "corrupt TFRecord length in {}".format(path))
                record = f.read(length)
                if len(record) != length:
                    raise IOError("truncated TFRecord in {}".format(path))
                crc_bytes = f.read(4)
                if len(crc_bytes) != 4:
                    raise IOError("truncated TFRecord in {}".format(path))
                if verify_crc:
                    (data_crc,) = struct.unpack("<I", crc_bytes)
                    if masked_crc32c(record) != data_crc:
                        raise IOError(
                            "corrupt TFRecord data in {}".format(path))
                yield record
