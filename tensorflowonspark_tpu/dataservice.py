"""Disaggregated data service: dispatcher + remote feed workers over TCP.

Per-host input pipelines cap accelerator utilization once a model is
input-bound — the tf.data service (arXiv:2210.14826) shows the fix is to
move input processing onto a horizontally-scalable fleet of feed workers
and keep only a thin client on the accelerator hosts.  This module
composes the framework's existing planes into exactly that shape:

- :class:`DispatcherServer` — control plane.  Registers workers, owns the
  split ledger for each dataset job (sharding modes :data:`SHARD_OFF` /
  :data:`SHARD_STATIC` / :data:`SHARD_DYNAMIC`), monitors worker liveness
  with the same heartbeat/fencing semantics as the rendezvous
  (:mod:`~tensorflowonspark_tpu.reservation`), and reassigns the splits of
  dead workers so every split is visited **exactly once per epoch**
  (tf.data's visitation guarantee, arXiv:2101.12127 §3.3).
- :class:`FeedWorker` — data plane producer.  Wraps a
  :class:`~tensorflowonspark_tpu.data.FileFeed` /
  :class:`~tensorflowonspark_tpu.data.ProcessPoolFeed` reader per split and
  streams row blocks to consumers as length-prefixed colv1 frames
  (:mod:`~tensorflowonspark_tpu.wire`) with pickle fallback for
  object/ragged columns — the same framability rules as the shm-ring
  feeder (``node._ChunkPutter``).
- :class:`ServiceFeed` — data plane consumer.  ``DataFeed``-compatible
  ``next_batch`` / ``next_batch_arrays`` surface, so ``ShardedFeed`` and
  ``train.fit_supervised`` consume it unchanged; receiver threads
  double-buffer network frames ahead of consumption and tally
  ``wire_formats`` + ``dataservice_*`` telemetry counters that ride node
  heartbeats into ``TPUCluster.metrics_snapshot()``.

Exactly-once protocol (STATIC / DYNAMIC): a split travels as
``split_begin`` → data frames → ``split_end`` on one worker→consumer
stream.  The consumer buffers the split's frames and **commits** only on
``split_end``: it publishes the buffered chunks to its batch queue
exactly once (the ``(epoch, split)`` dedupe set), then reports ``DONE``
to the dispatcher at-least-once (``DONE`` is idempotent; a failed report
parks and is retried by the maintainer thread).  Publish-before-DONE
means the ledger can never say a job is done while committed chunks are
still unpublished — the completion path waits for receivers and never
evicts the queue.  A worker death mid-split drops the connection before
``split_end`` — the consumer discards the partial buffer, reports the
split ``LOST`` so the dispatcher re-pools it immediately (worker fencing
remains the backstop), and a surviving worker — or a redial of the same,
still-live worker after a transient TCP reset — re-streams it.  The
dedupe set makes the race between a fenced-but-alive zombie worker and
the reassigned replacement harmless: whichever ``split_end`` lands first
wins, the other is discarded.  A split a worker cannot *read* is aborted
in-band (``split_abort`` + ``SPLIT_ERR``): the dispatcher re-pools it up
to a small budget, then fails the job with the reader's error.

Wire protocol: the dispatcher speaks the length-prefixed-JSON
``MessageSocket`` idiom of :mod:`~tensorflowonspark_tpu.reservation`
(``HBEAT``/``BYE`` are byte-compatible, so workers reuse
``HeartbeatSender`` verbatim).  Worker→consumer data streams use a 5-byte
``>IB`` prefix (payload length + kind): kind 0 JSON control, kind 1 a
colv1 frame, kind 2 pickled rows.

Multi-tenant v3 (tf.data-service shared jobs, arXiv:2210.14826 §4):

- **Shared jobs** — ``JOB`` is attach-or-create: a second run naming the
  same job with a compatible spec attaches as an additional *consumer*
  of the live ledger and the splits are handed out across all attached
  consumers exactly-once (each split streams to exactly ONE consumer; the
  runs split the read).  A consumer that detaches (``DETACH``) or goes
  silent past the heartbeat deadline has its bound splits rebound to the
  surviving consumers; a fenced consumer's later reports are refused
  under the same "fresh identity" rule as fenced workers.
- **Cache-affinity DYNAMIC scheduling** — workers advertise the source
  paths their :class:`_FrameCache` holds (registration + every
  heartbeat); the ledger's DYNAMIC hand-out gives a requesting worker a
  split it has cached, else one cached on no live worker (leaving warm
  splits for their holders), else FCFS head-of-queue, so pull-balancing
  is preserved and nothing ever waits on a cache holder.
- **Journaled dispatcher** — with ``journal_dir`` set, every ledger
  mutation is appended to a JSONL journal (flush-per-record) with
  periodic full snapshots; a SIGKILLed dispatcher restarted on the same
  port + journal dir replays the ledger and resumes in-flight jobs.
  Workers re-register when a heartbeat answer carries ``reregister``
  (the restarted dispatcher has never seen them); consumers reconnect
  lazily.  In-flight assignments recover as consumer-bound pending
  splits, so the consumer-side dedupe preserves exactly-once end to end.
"""

import collections
import json
import logging
import os
import pickle
import queue as _queue
import select
import socket
import struct
import threading
import time

import numpy as np

from tensorflowonspark_tpu import fault, marker, telemetry, transport, wire
from tensorflowonspark_tpu import standby as standby_mod
from tensorflowonspark_tpu.reservation import (
    Client, HeartbeatSender, KnobCoordinator, MessageSocket,
    normalize_endpoints)

logger = logging.getLogger(__name__)

__all__ = [
    "SHARD_OFF", "SHARD_STATIC", "SHARD_DYNAMIC", "DispatchError",
    "DispatcherServer", "DispatcherClient", "FeedWorker", "ServiceFeed",
]

#: No coordination: every worker→consumer stream delivers the FULL dataset
#: (``num_epochs`` times).  No visitation guarantee — with W workers a
#: consumer sees W copies per epoch.  The mode for sample-with-replacement
#: training where duplication is acceptable (tf.data service ShardingPolicy
#: OFF).
SHARD_OFF = "off"
#: Splits are owned by workers (round-robin over the worker roster frozen
#: at first assignment); a dead worker's remaining splits transfer to
#: survivors.  Exactly-once per epoch.
SHARD_STATIC = "static"
#: First-come-first-served: any worker pops the next unvisited split.
#: Self-balancing under heterogeneous workers.  Exactly-once per epoch.
SHARD_DYNAMIC = "dynamic"

_MODES = (SHARD_OFF, SHARD_STATIC, SHARD_DYNAMIC)

# Data-stream framing lives in transport.py now (shared with the serving
# gateway); the underscore aliases keep every internal call site and the
# tests that poke them unchanged.
_DHEADER = transport.DHEADER
_K_JSON = transport.K_JSON       # UTF-8 JSON control message
_K_COLV1 = transport.K_COLV1     # one wire.py colv1 frame (zero-copy decode)
_K_PICKLE = transport.K_PICKLE   # pickled row list (object/ragged fallback)

_SENTINEL = object()     # internal end-of-feed marker on the chunk queue
_INTERRUPTED = object()  # internal next_batch abort marker

#: Reader failures tolerated per split before the job fails with the
#: reader's error.  One re-pool covers a transient fault on one worker; a
#: split no worker can read must fail the job with a pointer to the file,
#: not wedge it.
_SPLIT_ERROR_BUDGET = 2


class DispatchError(RuntimeError):
    """The dispatcher answered ``ERR`` (unknown job, fenced worker, ...)."""


# ---------------------------------------------------------------------------
# Data-stream framing helpers (extracted to transport.py, re-exported here)
# ---------------------------------------------------------------------------

_SEND_COPY_MAX = transport.SEND_COPY_MAX
_recv_exact = transport.recv_exact
_recv_frame = transport.recv_frame
_send_frame = transport.send_frame
_send_json = transport.send_json
_addr_tuple = transport.addr_tuple


# ---------------------------------------------------------------------------
# Dispatcher: split ledger
# ---------------------------------------------------------------------------

class _Job(object):
    """Per-job split ledger (dispatcher-internal; all access serialized by
    the dispatcher's lock).

    Splits are file paths, identified by index.  Per epoch each split moves
    ``unassigned`` → ``assigned`` (bound to the ``(worker, consumer)`` that
    is streaming it) → ``completed`` (the consumer's ``DONE`` after a
    committed ``split_end``).  A worker death moves its assigned splits to
    ``pending[consumer]`` — still bound to the SAME consumer, so the
    consumer-side dedupe set covers every path a duplicate could take.

    Multi-tenant: ``consumers`` is the set of attached runs; a split is
    handed out once regardless of how many consumers are attached (the
    attached runs *split* the read).  :meth:`detach` rebinds a departing
    consumer's splits to survivors (or back to the pool), and a fenced
    consumer id can never re-attach (fresh-identity rule)."""

    def __init__(self, name, splits, num_epochs, mode):
        self.name = name
        self.splits = list(splits)
        self.num_epochs = int(num_epochs)
        self.mode = mode
        self.epoch = 0
        self.done = not self.splits or self.num_epochs <= 0
        self.error = None          # set => job failed (unreadable split)
        self.split_errors = {}     # split idx -> reader-failure count
        self.reassigned = 0        # splits re-pooled from dead workers (total)
        self.static_owner = None   # split idx -> worker_id (STATIC, lazy)
        self.off_served = set()    # (worker, consumer) streams served (OFF)
        self.consumers = set()     # attached consumer ids
        self.fenced_consumers = set()
        self.affinity_hits = 0     # DYNAMIC hand-outs landing on a holder
        self.affinity_total = 0    # all DYNAMIC hand-outs (A/B denominator)
        self._init_epoch()

    def _init_epoch(self):
        self.unassigned = list(range(len(self.splits)))
        self.assigned = {}   # split idx -> (worker_id, consumer_id)
        self.completed = set()
        self.pending = {}    # consumer_id -> [split idx] (death reassignments)

    def spec(self):
        return {"splits": self.splits, "num_epochs": self.num_epochs,
                "mode": self.mode}

    # -- consumers ---------------------------------------------------------

    def attach(self, consumer_id):
        """Attach a consumer; True when it is new to this job."""
        if not consumer_id or consumer_id in self.consumers:
            return False
        self.consumers.add(consumer_id)
        return True

    def detach(self, consumer_id, fence=False):
        """Detach a consumer and rebind its in-flight + pending splits to
        the surviving consumers (round-robin) or back to the unassigned
        pool when it was the last one.  ``fence=True`` additionally bans
        the id (liveness fencing — a fenced-but-alive consumer's later
        reports are refused, so its parked DONEs can never race the
        rebound copies).  Returns how many splits moved."""
        self.consumers.discard(consumer_id)
        if fence:
            self.fenced_consumers.add(consumer_id)
        orphans = [s for s, (w, c) in self.assigned.items()
                   if c == consumer_id]
        for s in orphans:
            del self.assigned[s]
        orphans.extend(self.pending.pop(consumer_id, []))
        heirs = sorted(self.consumers)
        moved = 0
        for i, s in enumerate(sorted(set(orphans))):
            if s in self.completed:
                continue
            self._unbind(s)
            if heirs:
                self.pending.setdefault(heirs[i % len(heirs)], []).append(s)
            else:
                self.unassigned.append(s)
            moved += 1
        self.reassigned += moved
        return moved

    def _unbind(self, split):
        """Remove a split from the unassigned pool and every pending list
        (so a rebind never leaves a second copy behind)."""
        if split in self.unassigned:
            self.unassigned.remove(split)
        for pend in self.pending.values():
            if split in pend:
                pend.remove(split)

    # -- assignment --------------------------------------------------------

    def _ensure_static_owners(self, live_workers):
        if self.static_owner is None:
            owners = sorted(live_workers)
            self.static_owner = {
                i: owners[i % len(owners)] if owners else None
                for i in range(len(self.splits))}

    def _pick(self, candidates, worker_id, worker_caches, affinity):
        """The next DYNAMIC split for ``worker_id`` out of ``candidates``
        (non-empty).  With affinity on, prefer (a) a split this worker's
        cache holds, then (b) one no live worker holds — leaving warm
        splits for their holders while they still have cold work — and
        only then (c) the FCFS head.  (c) means a cold worker is never
        starved waiting on a cache holder: availability wins at the tail,
        which is the pull-scheduling analogue of least-loaded fallback."""
        if not affinity or not worker_caches:
            return candidates[0]
        mine = worker_caches.get(worker_id) or ()
        for s in candidates:
            if self.splits[s] in mine:
                return s
        held = set()
        for w, paths in worker_caches.items():
            if w != worker_id:
                held.update(paths)
        if held:
            for s in candidates:
                if self.splits[s] not in held:
                    return s
        return candidates[0]

    def _bind(self, split, worker_id, consumer_id, worker_caches):
        self.assigned[split] = (worker_id, consumer_id)
        if self.mode == SHARD_DYNAMIC:
            # tallied for EVERY dynamic hand-out, affinity knob on or off,
            # so the A/B bench can compare hit rates between the two
            self.affinity_total += 1
            if (worker_caches
                    and self.splits[split] in
                    (worker_caches.get(worker_id) or ())):
                self.affinity_hits += 1
        return {"splits": [[split, self.splits[split]]], "epoch": self.epoch}

    def next_splits(self, worker_id, consumer_id, live_workers,
                    worker_caches=None, affinity=False):
        """One TASK answer: ``{"splits": [[idx, path]], "epoch": e}``, or
        ``{"wait": True}`` (epoch still completing / nothing for this
        worker yet), or ``{"done": True}`` (job exhausted).

        ``worker_caches`` maps worker id → set of cached source paths (the
        heartbeat advertisement); with ``affinity`` DYNAMIC hand-outs —
        fresh and re-pooled alike — prefer cache holders (:meth:`_pick`)."""
        if self.mode == SHARD_OFF:
            key = (worker_id, consumer_id)
            if self.done or key in self.off_served:
                return {"done": True}
            self.off_served.add(key)
            return {"splits": [[i, p] for i, p in enumerate(self.splits)],
                    "epoch": 0, "epochs": self.num_epochs}
        if self.done:
            return {"done": True}
        dyn = self.mode == SHARD_DYNAMIC
        # 1. death-reassigned splits bound to this consumer (any worker may
        #    serve them — the original owner is gone)
        pend = self.pending.get(consumer_id)
        if pend:
            # the zombie's copy already landed / re-pooled twice: drop those
            valid = [s for s in pend
                     if s not in self.completed and s not in self.assigned]
            self.pending[consumer_id] = valid
            if valid:
                s = (self._pick(valid, worker_id, worker_caches, affinity)
                     if dyn else valid[0])
                valid.remove(s)
                return self._bind(s, worker_id, consumer_id, worker_caches)
        # 2. fresh splits
        if self.mode == SHARD_STATIC:
            self._ensure_static_owners(live_workers)
            for i, s in enumerate(self.unassigned):
                owner = self.static_owner.get(s)
                if owner is None or owner == worker_id:
                    self.unassigned.pop(i)
                    return self._bind(s, worker_id, consumer_id,
                                      worker_caches)
        elif self.unassigned:
            s = self._pick(self.unassigned, worker_id, worker_caches,
                           affinity)
            self.unassigned.remove(s)
            return self._bind(s, worker_id, consumer_id, worker_caches)
        return {"wait": True}

    def complete(self, epoch, split, consumer_id):
        """Consumer's ``DONE`` for a committed split; idempotent."""
        if (self.mode == SHARD_OFF or self.done or self.error is not None
                or epoch != self.epoch):
            return {"ok": True, "stale": True}
        if split in self.completed:
            return {"ok": True, "duplicate": True}
        self.completed.add(split)
        self.assigned.pop(split, None)
        for pend in self.pending.values():
            if split in pend:
                pend.remove(split)
        if len(self.completed) == len(self.splits):
            self.epoch += 1
            if self.epoch >= self.num_epochs:
                self.done = True
            else:
                self._init_epoch()
        return {"ok": True}

    def release_worker(self, worker_id, live_workers):
        """Re-pool a dead (or departing) worker's uncompleted splits; STATIC
        ownership of its unstarted splits transfers to survivors.  Returns
        the re-pooled ``(split, consumer)`` bindings (for the journal)."""
        moved = []
        for s, (w, consumer) in list(self.assigned.items()):
            if w == worker_id:
                del self.assigned[s]
                self.pending.setdefault(consumer, []).append(s)
                moved.append((s, consumer))
        if self.mode == SHARD_STATIC and self.static_owner:
            survivors = sorted(w for w in live_workers if w != worker_id)
            n = 0
            for s, owner in list(self.static_owner.items()):
                if owner == worker_id:
                    self.static_owner[s] = (
                        survivors[n % len(survivors)] if survivors else None)
                    n += 1
        self.reassigned += len(moved)
        return moved

    def release_split(self, epoch, split, worker_id, consumer_id):
        """Re-pool one split whose worker→consumer stream broke while the
        worker may still be alive (the consumer's ``LOST`` report) —
        recovery without waiting for a heartbeat fence.  Idempotent and
        stale-safe like :meth:`complete`."""
        if (self.mode == SHARD_OFF or self.done or self.error is not None
                or epoch != self.epoch or split in self.completed):
            return {"ok": True, "stale": True}
        if self.assigned.get(split) != (worker_id, consumer_id):
            return {"ok": True, "stale": True}
        del self.assigned[split]
        self.pending.setdefault(consumer_id, []).append(split)
        self.reassigned += 1
        return {"ok": True}

    def record_split_error(self, epoch, split, worker_id, consumer_id, desc):
        """A worker failed to READ a split (its stream is intact).  Re-pool
        it for another attempt up to :data:`_SPLIT_ERROR_BUDGET`; past the
        budget the job fails carrying the reader's error, so consumers
        surface the cause instead of retrying an unreadable file forever."""
        if (self.mode == SHARD_OFF or self.done or self.error is not None
                or epoch != self.epoch or split in self.completed):
            return {"ok": True, "stale": True}
        if self.assigned.get(split) == (worker_id, consumer_id):
            del self.assigned[split]
        n = self.split_errors.get(split, 0) + 1
        self.split_errors[split] = n
        if n >= _SPLIT_ERROR_BUDGET:
            self.error = ("split {} ({!r}) unreadable after {} attempt(s), "
                          "last on worker {}: {}".format(
                              split, self.splits[split], n, worker_id, desc))
            return {"ok": True, "failed": True}
        self.pending.setdefault(consumer_id, []).append(split)
        self.reassigned += 1
        return {"ok": True}

    def status(self):
        return {"job": self.name, "mode": self.mode, "epoch": self.epoch,
                "num_epochs": self.num_epochs, "error": self.error,
                "num_splits": len(self.splits), "done": self.done,
                "completed": len(self.completed),
                "assigned": len(self.assigned),
                "pending": sum(len(v) for v in self.pending.values()),
                "reassigned": self.reassigned,
                "consumers": len(self.consumers),
                "affinity_hits": self.affinity_hits,
                "affinity_total": self.affinity_total}

    # -- journal state -----------------------------------------------------

    def to_state(self):
        """JSON-serializable full ledger state (snapshot records)."""
        return {
            "name": self.name, "splits": list(self.splits),
            "num_epochs": self.num_epochs, "mode": self.mode,
            "epoch": self.epoch, "done": self.done, "error": self.error,
            "split_errors": sorted(self.split_errors.items()),
            "reassigned": self.reassigned,
            "static_owner": (sorted(self.static_owner.items())
                             if self.static_owner is not None else None),
            "off_served": sorted(list(k) for k in self.off_served),
            "unassigned": list(self.unassigned),
            "assigned": sorted([s, w, c]
                               for s, (w, c) in self.assigned.items()),
            "completed": sorted(self.completed),
            "pending": {c: list(v) for c, v in self.pending.items()},
            "consumers": sorted(self.consumers),
            "fenced_consumers": sorted(self.fenced_consumers),
        }

    @classmethod
    def from_state(cls, state):
        job = cls(state["name"], state["splits"],
                  state["num_epochs"], state["mode"])
        job.epoch = int(state["epoch"])
        job.done = bool(state["done"])
        job.error = state.get("error")
        job.split_errors = {int(k): int(v)
                            for k, v in state.get("split_errors", [])}
        job.reassigned = int(state.get("reassigned", 0))
        so = state.get("static_owner")
        job.static_owner = ({int(k): v for k, v in so}
                            if so is not None else None)
        job.off_served = set(tuple(k) for k in state.get("off_served", []))
        job.unassigned = [int(s) for s in state.get("unassigned", [])]
        job.assigned = {int(s): (w, c)
                        for s, w, c in state.get("assigned", [])}
        job.completed = set(int(s) for s in state.get("completed", []))
        job.pending = {c: [int(s) for s in v]
                       for c, v in (state.get("pending") or {}).items()}
        job.consumers = set(state.get("consumers", []))
        job.fenced_consumers = set(state.get("fenced_consumers", []))
        return job


# ---------------------------------------------------------------------------
# DispatcherServer
# ---------------------------------------------------------------------------

def _env_int(name, default):
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        logger.warning("ignoring non-integer %s=%r", name, raw)
        return default


def _env_flag(name, default):
    raw = os.environ.get(name, "").strip().lower()
    if not raw:
        return default
    return raw not in ("0", "false", "off", "no")


class DispatcherServer(MessageSocket):
    """Data-service control plane: worker registry + split ledgers.

    Single listener thread multiplexing all connections with ``select``
    (the :class:`~tensorflowonspark_tpu.reservation.Server` idiom); worker
    liveness uses the same fencing semantics — a worker past
    ``interval × misses`` of heartbeat silence is declared dead, its beats
    are rejected from then on (``HeartbeatSender`` stops itself on the
    fence answer), and its uncompleted splits are re-pooled.

    Message types (length-prefixed JSON): ``WREG`` (worker registration),
    ``HBEAT``/``BYE`` (byte-compatible with the rendezvous, so workers
    reuse ``HeartbeatSender``), ``JOB`` (attach-or-create job
    registration), ``DETACH`` (consumer departure: rebind its splits),
    ``WORKERS`` (live roster for consumers), ``TASK`` (split request),
    ``DONE`` (consumer's split-visited report), ``LOST`` (consumer's
    broken-stream report: re-pool the mid-flight split without waiting
    for a fence), ``SPLIT_ERR`` (worker's reader-fault report: re-pool up
    to a budget, then fail the job with the cause), ``STATUS``, ``STOP``.

    Durability: with ``journal_dir`` set (or ``TFOS_DS_JOURNAL_DIR``),
    every ledger mutation appends one JSONL record to the current journal
    segment, flushed per record; every ``snapshot_every`` records the
    full state is snapshotted (``snapshot-<seq>.json``, atomic
    tmp+rename) and a fresh segment (``journal-<seq>.jsonl``) starts.
    :meth:`start` recovers from the newest snapshot plus its segment
    before accepting connections — in-flight assignments come back as
    consumer-bound pending splits (the assigned workers' streams died
    with the old process), so the consumer-side dedupe keeps visitation
    exactly-once across the restart.

    ``affinity`` (default on; ``TFOS_DS_AFFINITY=0`` to disable) enables
    cache-affinity DYNAMIC hand-out from the worker cache advertisements
    riding WREG and HBEAT.  ``port`` pins the listen port (0 = ephemeral)
    so a restarted dispatcher is reachable at the old address.
    """

    def __init__(self, heartbeat_interval=1.0, heartbeat_misses=3,
                 host=None, port=0, journal_dir=None, snapshot_every=None,
                 affinity=None, journal_keep=None, journal_keep_bytes=None,
                 beacon_interval=None, takeover_grace=None):
        self.heartbeat_interval = heartbeat_interval
        self.heartbeat_misses = heartbeat_misses
        self._host = host
        self._port = int(port)
        if beacon_interval is None:
            beacon_interval = (min(max(heartbeat_interval / 2.0, 0.1), 0.5)
                               if heartbeat_interval else 0.5)
        self.beacon_interval = float(beacon_interval)
        self._takeover_grace = takeover_grace
        # Fencing epoch: 0 until this incarnation claims a journal dir; see
        # reservation.Server — same protocol, same standby module.
        self.fencing_epoch = 0
        self.superseded_by = None
        self.journal_records = 0
        self._beacon_last = 0.0
        self._fence_grace_until = 0.0
        if journal_dir is None:
            journal_dir = os.environ.get("TFOS_DS_JOURNAL_DIR") or None
        self.journal_dir = journal_dir
        if snapshot_every is None:
            snapshot_every = _env_int("TFOS_DS_SNAPSHOT_EVERY", 512)
        self.snapshot_every = max(int(snapshot_every), 1)
        # Compaction policy: keep the newest ``journal_keep`` generations
        # (snapshot + its segment; the historic hardcoded default was 2),
        # or — when ``journal_keep_bytes`` is set — as many newest
        # generations as fit the byte budget (week-long shared jobs want
        # "a disk budget", not "a count"; the newest generation is always
        # kept regardless).
        if journal_keep is None:
            journal_keep = _env_int("TFOS_DS_JOURNAL_KEEP", 2)
        self.journal_keep = max(int(journal_keep), 1)
        if journal_keep_bytes is None:
            journal_keep_bytes = _env_int("TFOS_DS_JOURNAL_KEEP_BYTES", 0)
        self.journal_keep_bytes = max(int(journal_keep_bytes), 0)
        if affinity is None:
            affinity = _env_flag("TFOS_DS_AFFINITY", True)
        self.affinity = bool(affinity)
        self._jobs = {}      # name -> _Job
        self._workers = {}   # worker_id -> {"worker_id","host","port"}
        self._beats = {}     # worker_id -> last beat (monotonic)
        self._dead = {}      # worker_id -> death description
        self._worker_metrics = {}  # worker_id -> latest HBEAT counters
        self._worker_cache = {}    # worker_id -> cached source-path set
        self._consumer_seen = {}   # (job, consumer) -> last contact
        # Live-knob fan-out to workers: the driver-side autopilot can't
        # reach FeedWorkers directly (they beat HERE, not to the
        # reservation server), so a KNOB message queues updates that ride
        # out on worker HBEAT replies exactly-once (the same coordinator
        # the reservation server uses for training nodes).
        self.knobs = KnobCoordinator()
        self._journal_file = None
        self._journal_seq = 0
        self._journal_count = 0
        self.recovered_jobs = 0    # jobs rebuilt from the journal at start
        self._lock = threading.RLock()
        self._stopping = False
        self._socket = None
        self._thread = None

    # -- snapshots (any thread) -------------------------------------------

    def workers(self):
        """Live worker roster: ``{worker_id: {worker_id, host, port}}``."""
        with self._lock:
            return {w: dict(meta) for w, meta in self._workers.items()}

    def dead_workers(self):
        """Fenced-worker descriptions keyed by worker id."""
        with self._lock:
            return dict(self._dead)

    def worker_metrics(self):
        """Latest per-worker HBEAT counters plus a merged aggregate.

        Returns ``{"workers": {worker_id: counters}, "aggregate": counters}``
        where the aggregate follows :func:`telemetry.merge_counters`
        semantics (``_hwm``/``_max`` keys merge by max, the rest sum)."""
        with self._lock:
            per = {w: dict(c) for w, c in self._worker_metrics.items()}
        return {"workers": per,
                "aggregate": telemetry.merge_counters(per.values())}

    def job_status(self, name):
        """Ledger snapshot for one job (``None`` if unknown)."""
        with self._lock:
            job = self._jobs.get(name)
            return job.status() if job is not None else None

    # -- fencing epoch + reply stamping (see reservation.Server) -----------

    def send(self, sock, msg):
        # Stamped under "fence_epoch", NOT "epoch": TASK replies already
        # carry the job's DATA epoch as "epoch", and a client reading a
        # fresh job's epoch 0 as a fencing epoch would refuse a healthy
        # dispatcher (DispatcherClient._fence_epoch_key matches this key).
        if self.fencing_epoch and isinstance(msg, dict):
            msg.setdefault("fence_epoch", self.fencing_epoch)
        MessageSocket.send(self, sock, msg)

    def _check_epoch(self):
        """Ledger-ownership check: a newer fencing epoch on disk means a
        successor (restart or promoted standby) claimed the journal — this
        incarnation fences itself and answers everything ERR."""
        if not self.journal_dir or self.superseded_by is not None:
            return
        on_disk = standby_mod.read_epoch(self.journal_dir)
        if on_disk > self.fencing_epoch:
            self.superseded_by = on_disk
            logger.error(
                "dispatcher fenced: epoch %d on disk supersedes this "
                "incarnation's epoch %d — a successor owns the ledger",
                on_disk, self.fencing_epoch)
            telemetry.get_tracer().instant(
                "dataservice/zombie_fenced", epoch=self.fencing_epoch,
                superseded_by=on_disk)
            if self._journal_file is not None:
                try:
                    self._journal_file.close()
                except OSError:
                    pass
                self._journal_file = None

    def _stamp_beacon(self, addr, force=False):
        if not self.journal_dir or self.superseded_by is not None:
            return
        now = time.monotonic()
        if not force and now - self._beacon_last < self.beacon_interval:
            return
        self._beacon_last = now
        self._check_epoch()
        if self.superseded_by is None:
            standby_mod.write_beacon(self.journal_dir, self.fencing_epoch,
                                     host=addr[0], port=addr[1],
                                     role="dispatcher")

    def ha_status(self):
        """Coordinator-HA block for ``/status`` + ``tfos_coordinator_*``."""
        return {
            "journal_dir": self.journal_dir,
            "epoch": self.fencing_epoch,
            "superseded_by": self.superseded_by,
            "recovered_nodes": self.recovered_jobs,
            "recoveries": 1 if self.recovered_jobs else 0,
            "journal_records": self.journal_records,
            "snapshot_seq": self._journal_seq,
            "grace_remaining_secs": round(
                max(0.0, self._fence_grace_until - time.monotonic()), 3),
        }

    # -- journal (caller holds the lock) -----------------------------------

    def _segment_path(self, kind, seq):
        ext = "jsonl" if kind == "journal" else "json"
        return os.path.join(self.journal_dir,
                            "{}-{:08d}.{}".format(kind, seq, ext))

    def _journal(self, rec):
        """Append one ledger-mutation record; flush-per-record so a SIGKILL
        loses at most the record being written (a torn tail line, skipped
        on replay).  A journal write failure degrades to in-memory-only
        operation with a loud log — availability over durability."""
        if self._journal_file is None:
            return
        self._check_epoch()  # never append past a successor's claim
        if self._journal_file is None:
            return
        try:
            self._journal_file.write(json.dumps(rec, sort_keys=True) + "\n")
            self._journal_file.flush()
        except (OSError, ValueError) as e:
            logger.error("dataservice journal: write failed (%s); ledger "
                         "durability is LOST until restart", e)
            try:
                self._journal_file.close()
            except OSError:
                pass
            self._journal_file = None
            return
        self.journal_records += 1
        self._journal_count += 1
        if self._journal_count >= self.snapshot_every:
            self._write_snapshot()

    def _write_snapshot(self):
        """Full-state snapshot (atomic tmp+rename) + fresh journal segment;
        segments older than the previous generation are pruned."""
        self._journal_seq += 1
        seq = self._journal_seq
        state = {"seq": seq,
                 "jobs": {n: j.to_state() for n, j in self._jobs.items()},
                 "dead_workers": dict(self._dead)}
        path = self._segment_path("snapshot", seq)
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(state, f, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            if self._journal_file is not None:
                self._journal_file.close()
            self._journal_file = open(self._segment_path("journal", seq), "a")
        except OSError as e:
            logger.error("dataservice journal: snapshot %d failed (%s)",
                         seq, e)
            self._journal_file = None
        self._journal_count = 0
        self._prune_segments(seq)

    def _gen_bytes(self, seq):
        """On-disk bytes of one generation (snapshot + journal segment)."""
        total = 0
        for kind in ("snapshot", "journal"):
            try:
                total += os.path.getsize(self._segment_path(kind, seq))
            except OSError:
                pass
        return total

    def _prune_segments(self, seq):
        """Apply the compaction policy after cutting generation ``seq``.

        Byte budget (``journal_keep_bytes`` > 0): keep the newest
        generations whose cumulative on-disk size fits the budget — the
        newest is always kept even when it alone overflows.  Otherwise:
        keep the newest ``journal_keep`` generations.  Everything older
        is unlinked."""
        if self.journal_keep_bytes:
            keep = {seq}
            total = self._gen_bytes(seq)
            for s in range(seq - 1, 0, -1):
                total += self._gen_bytes(s)
                if total > self.journal_keep_bytes:
                    break
                keep.add(s)
            oldest_kept = min(keep)
        else:
            oldest_kept = seq - self.journal_keep + 1
        for old in range(1, oldest_kept):
            for kind in ("snapshot", "journal"):
                try:
                    os.unlink(self._segment_path(kind, old))
                except OSError:
                    pass

    def _replay(self, rec):
        """Apply one journal record to the ledger (same mutation paths as
        the live handlers, so replay and live execution cannot diverge)."""
        t = rec.get("t")
        if t == "job":
            if rec["job"] not in self._jobs:
                self._jobs[rec["job"]] = _Job(
                    rec["job"], rec["splits"], rec["num_epochs"], rec["mode"])
            return
        if t == "fence":
            self._dead[rec["worker"]] = rec.get(
                "why", "fenced before a dispatcher restart")
            return
        job = self._jobs.get(rec.get("job"))
        if job is None:
            return
        if t == "attach":
            job.attach(rec["consumer"])
        elif t == "detach":
            job.detach(rec["consumer"], fence=bool(rec.get("fence")))
        elif t in ("assign", "repool"):
            s = int(rec["split"])
            if (int(rec.get("epoch", 0)) == job.epoch
                    and not job.done and s not in job.completed):
                # the stream (if any) died with the old dispatcher's
                # workers: recover the binding as consumer-bound pending
                job.assigned.pop(s, None)
                job._unbind(s)
                job.pending.setdefault(rec["consumer"], []).append(s)
        elif t == "done":
            job.complete(int(rec.get("epoch", 0)), int(rec["split"]),
                         rec.get("consumer"))
        elif t == "split_err":
            job.record_split_error(
                int(rec.get("epoch", 0)), int(rec["split"]),
                rec.get("worker"), rec.get("consumer"),
                rec.get("error") or "reader failure")

    def _recover(self):
        """Rebuild the ledger from the newest snapshot + its journal
        segment, then re-pool every recovered in-flight assignment (those
        workers' streams are gone) and cut a fresh snapshot so the next
        restart replays from here."""
        os.makedirs(self.journal_dir, exist_ok=True)
        seqs = []
        for name in os.listdir(self.journal_dir):
            if name.startswith("snapshot-") and name.endswith(".json"):
                try:
                    seqs.append(int(name[len("snapshot-"):-len(".json")]))
                except ValueError:
                    pass
        seq = max(seqs) if seqs else 0
        if seq:
            try:
                with open(self._segment_path("snapshot", seq)) as f:
                    state = json.load(f)
                self._jobs = {n: _Job.from_state(s)
                              for n, s in state.get("jobs", {}).items()}
                self._dead.update(state.get("dead_workers") or {})
                self._journal_seq = int(state.get("seq", seq))
            except (OSError, ValueError, KeyError) as e:
                logger.error("dataservice journal: snapshot %d unreadable "
                             "(%s); replaying the journal from scratch",
                             seq, e)
                self._jobs, self._journal_seq = {}, seq
        replayed = 0
        for jseq in sorted(s for s in self._list_segments() if s >= seq):
            try:
                with open(self._segment_path("journal", jseq)) as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            break  # torn tail record from the SIGKILL
                        self._replay(rec)
                        replayed += 1
            except OSError:
                continue
        for job in self._jobs.values():
            for s, (w, c) in list(job.assigned.items()):
                del job.assigned[s]
                if s not in job.completed:
                    job._unbind(s)
                    job.pending.setdefault(c, []).append(s)
        # arm consumer liveness for every recovered consumer: one that died
        # while the dispatcher was down never makes contact again and must
        # be fenced by silence like any other
        now = time.monotonic()
        for job in self._jobs.values():
            if job.done or job.mode == SHARD_OFF:
                continue
            for c in job.consumers:
                self._consumer_seen[(job.name, c)] = now
        self.recovered_jobs = len(self._jobs)
        if self.recovered_jobs:
            # Fence-free grace while recovered workers and consumers
            # re-home to this incarnation; a fresh (journal-less history)
            # dispatcher sets none, so first starts behave exactly as
            # before.
            grace = self._takeover_grace
            if grace is None:
                grace = max(
                    self.heartbeat_interval * self.heartbeat_misses, 2.0)
            self._fence_grace_until = now + grace
        if self._jobs or replayed or seq:
            logger.warning(
                "dataservice dispatcher: recovered %d job(s) from %s "
                "(snapshot %d + %d journal record(s))",
                len(self._jobs), self.journal_dir, seq, replayed)
            telemetry.get_tracer().instant(
                "dataservice/dispatcher_recover", jobs=len(self._jobs),
                records=replayed)
        self._write_snapshot()

    def _list_segments(self):
        out = []
        for name in os.listdir(self.journal_dir):
            if name.startswith("journal-") and name.endswith(".jsonl"):
                try:
                    out.append(int(name[len("journal-"):-len(".jsonl")]))
                except ValueError:
                    pass
        return out

    # -- ledger mutation (listener thread, under lock) ---------------------

    def _register_worker(self, meta):
        worker_id = meta.get("worker_id")
        if not worker_id or "host" not in meta or "port" not in meta:
            return "worker registration needs worker_id, host, port"
        if worker_id in self._dead:
            return ("worker {} was fenced by the liveness monitor; a "
                    "replacement must register with a fresh identity"
                    .format(worker_id))
        if worker_id in self._workers:
            return "duplicate worker id {}".format(worker_id)
        self._workers[worker_id] = {"worker_id": worker_id,
                                    "host": meta["host"],
                                    "port": int(meta["port"])}
        self._beats[worker_id] = time.monotonic()
        cached = meta.get("cache_splits")
        if cached is not None:
            self._worker_cache[worker_id] = set(cached)
        telemetry.get_tracer().instant(
            "dataservice/worker_register", worker_id=worker_id,
            workers=len(self._workers))
        return None

    def _release_worker(self, worker_id, why):
        """Drop a worker from the roster and re-pool its splits."""
        self._workers.pop(worker_id, None)
        self._beats.pop(worker_id, None)
        self._worker_cache.pop(worker_id, None)
        live = list(self._workers)
        moved = 0
        for job in self._jobs.values():
            repooled = job.release_worker(worker_id, live)
            for split, consumer in repooled:
                self._journal({"t": "repool", "job": job.name,
                               "epoch": job.epoch, "split": split,
                               "consumer": consumer})
            moved += len(repooled)
        if moved:
            logger.warning("dataservice: re-pooled %d split(s) from worker "
                           "%s (%s)", moved, worker_id, why)
            telemetry.get_tracer().instant(
                "dataservice/split_reassign", worker_id=worker_id,
                splits=moved, why=why)

    def _check_liveness(self):
        if not self.heartbeat_interval:
            return
        now = time.monotonic()
        if now < self._fence_grace_until:
            # Post-takeover grace: recovered workers/consumers were beating
            # at the dead predecessor; their silence is our history, not a
            # death — let them re-home before fencing anyone.
            return
        deadline = self.heartbeat_interval * self.heartbeat_misses
        with self._lock:
            for worker_id, last in list(self._beats.items()):
                age = now - last
                if age > deadline and worker_id in self._workers:
                    desc = ("feed worker {} missed {} heartbeats (last beat "
                            "{:.1f}s ago, interval {:.1f}s)").format(
                                worker_id, self.heartbeat_misses, age,
                                self.heartbeat_interval)
                    logger.error("dataservice liveness: %s", desc)
                    self._dead[worker_id] = desc
                    self._journal({"t": "fence", "worker": worker_id,
                                   "why": desc})
                    telemetry.get_tracer().instant(
                        "dataservice/worker_dead", worker_id=worker_id,
                        age_secs=round(age, 3))
                    self._release_worker(worker_id, "dead")
            # consumer liveness: any JOB/TASK/DONE/LOST/STATUS contact
            # naming a consumer refreshes it; silence past the worker
            # deadline fences the consumer and rebinds its splits to the
            # survivors (or back to the pool) so a shared job never wedges
            # on a crashed run
            for key, last in list(self._consumer_seen.items()):
                if now - last <= deadline:
                    continue
                del self._consumer_seen[key]
                jobname, consumer = key
                job = self._jobs.get(jobname)
                if (job is None or job.done or job.error is not None
                        or job.mode == SHARD_OFF
                        or consumer not in job.consumers):
                    continue
                moved = job.detach(consumer, fence=True)
                self._journal({"t": "detach", "job": jobname,
                               "consumer": consumer, "fence": True})
                logger.error(
                    "dataservice liveness: consumer %s of job %r went "
                    "silent; fenced, %d split(s) rebound", consumer,
                    jobname, moved)
                telemetry.get_tracer().instant(
                    "dataservice/consumer_dead", job=jobname,
                    consumer=consumer, splits=moved)

    def _touch_consumer(self, job, consumer_id):
        """Record consumer contact (liveness only applies to ledger modes;
        OFF-mode jobs have no per-consumer bindings to rebind)."""
        if job is not None and consumer_id and job.mode != SHARD_OFF:
            self._consumer_seen[(job.name, consumer_id)] = time.monotonic()

    def _handle_job(self, sock, data):
        """Attach-or-create job registration.

        ``attach`` in the request is ``"auto"`` (create the job if absent,
        attach otherwise — the shared-job default), ``"create"`` (refuse an
        existing job) or ``"attach"`` (refuse a missing one; ``splits`` may
        be omitted and the reply's ``spec`` adopted).  An existing job with
        an incompatible spec is always an error; so is attaching to a
        finished/failed job or with a fenced consumer id."""
        name = data.get("name")
        consumer = data.get("consumer_id")
        attach_mode = data.get("attach", "auto")
        job = self._jobs.get(name)
        spec = None
        if data.get("splits") is not None:
            spec = {"splits": list(data.get("splits") or []),
                    "num_epochs": int(data.get("num_epochs", 1)),
                    "mode": data.get("mode", SHARD_DYNAMIC)}
            if spec["mode"] not in _MODES:
                self.send(sock, {"type": "ERR",
                                 "error": "unknown sharding mode {!r}"
                                          .format(spec["mode"])})
                return
        if job is not None and consumer in job.fenced_consumers:
            self.send(sock, {"type": "ERR",
                             "error": "consumer {} of job {!r} was fenced "
                                      "by the liveness monitor; a new run "
                                      "must attach with a fresh identity"
                                      .format(consumer, name)})
            return
        if job is None:
            if attach_mode == "attach":
                self.send(sock, {"type": "ERR",
                                 "error": "job {!r} does not exist: nothing "
                                          "to attach to".format(name)})
                return
            if spec is None:
                self.send(sock, {"type": "ERR",
                                 "error": "job {!r} needs splits to be "
                                          "created".format(name)})
                return
            job = _Job(name, spec["splits"], spec["num_epochs"],
                       spec["mode"])
            self._jobs[name] = job
            self._journal({"t": "job", "job": name, "splits": spec["splits"],
                           "num_epochs": spec["num_epochs"],
                           "mode": spec["mode"]})
            telemetry.get_tracer().instant(
                "dataservice/job", job=name, mode=spec["mode"],
                splits=len(spec["splits"]), num_epochs=spec["num_epochs"])
            created = True
        else:
            if attach_mode == "create":
                self.send(sock, {"type": "ERR",
                                 "error": "job {!r} already exists "
                                          "(attach=False)".format(name)})
                return
            if spec is not None and job.spec() != spec:
                self.send(sock, {"type": "ERR",
                                 "error": "job {!r} already exists with a "
                                          "different spec".format(name)})
                return
            if job.error is not None:
                self.send(sock, {"type": "ERR",
                                 "error": "job {!r} failed: {}".format(
                                     name, job.error)})
                return
            created = False
        if job.attach(consumer):
            self._journal({"t": "attach", "job": name, "consumer": consumer})
            telemetry.get_tracer().instant(
                "dataservice/consumer_attach", job=name, consumer=consumer,
                consumers=len(job.consumers))
        self._touch_consumer(job, consumer)
        reply = dict(job.spec())
        self.send(sock, {"type": "OK", "created": created,
                         "spec": reply, "epoch": job.epoch,
                         "done": job.done,
                         "consumers": len(job.consumers)})

    def _handle_message(self, sock, msg):
        mtype = msg.get("type")
        data = msg.get("data") or {}
        with self._lock:
            if mtype in ("WREG", "HBEAT", "BYE", "JOB", "DETACH", "TASK",
                         "DONE", "LOST", "KNOB"):
                # Mutating request: re-verify ledger ownership FIRST so a
                # zombie dispatcher never mutates state its successor
                # doesn't have (and never replies OK for it).
                self._check_epoch()
            if self.superseded_by is not None:
                self.send(sock, {
                    "type": "ERR", "fence_epoch": self.superseded_by,
                    "superseded": self.superseded_by,
                    "error": "dispatcher superseded: epoch {} claimed the "
                             "ledger (this incarnation was epoch {}); "
                             "redial the promoted dispatcher".format(
                                 self.superseded_by, self.fencing_epoch)})
                return True
            if mtype == "WREG":
                err = self._register_worker(data)
                if err:
                    logger.warning("rejecting worker registration: %s", err)
                    self.send(sock, {"type": "ERR", "error": err})
                else:
                    self.send(sock, {"type": "OK"})
            elif mtype == "HBEAT":
                worker_id = data.get("executor_id")
                if worker_id in self._dead:
                    self.send(sock, {"type": "ERR",
                                     "error": "marked dead by the liveness "
                                              "monitor"})
                else:
                    # beats from ids we never saw register are tracked too
                    # (mirrors reservation.Server._beat)
                    reply = {"type": "OK"}
                    if worker_id is not None:
                        self._beats[worker_id] = time.monotonic()
                        beat_metrics = data.get("metrics")
                        if isinstance(beat_metrics, dict):
                            # the cache advertisement rides the metrics dict
                            # but is a path list, not a counter: strip it
                            # before the merge-by-sum vocabulary sees it
                            paths = beat_metrics.pop("cache_paths", None)
                            if paths is not None:
                                self._worker_cache[worker_id] = set(paths)
                            self._worker_metrics.setdefault(
                                worker_id, {}).update(beat_metrics)
                        if worker_id not in self._workers:
                            # a restarted dispatcher has never seen this
                            # worker: tell it to re-register (WREG) so it
                            # re-enters the roster with its data address
                            reply["reregister"] = True
                        # live-knob fan-out: pending KNOB pushes ride the
                        # beat reply exactly-once per worker
                        try:
                            pending = self.knobs.poll(worker_id)
                        except Exception:
                            logger.exception("worker knob poll failed")
                            pending = None
                        if pending:
                            reply["knobs"] = pending
                    self.send(sock, reply)
            elif mtype == "BYE":
                worker_id = data.get("executor_id")
                if worker_id is not None and worker_id in self._workers:
                    self._release_worker(worker_id, "bye")
                self.send(sock, {"type": "OK"})
            elif mtype == "JOB":
                self._handle_job(sock, data)
            elif mtype == "DETACH":
                job = self._jobs.get(data.get("job"))
                consumer = data.get("consumer_id")
                if job is None or not consumer:
                    self.send(sock, {"type": "OK", "stale": True})
                elif consumer not in job.consumers:
                    # duplicate departure (or a never-attached name): stale,
                    # not an error — DETACH is the best-effort exit path
                    self._consumer_seen.pop((job.name, consumer), None)
                    self.send(sock, {"type": "OK", "stale": True})
                else:
                    moved = job.detach(consumer)
                    self._journal({"t": "detach", "job": job.name,
                                   "consumer": consumer})
                    telemetry.get_tracer().instant(
                        "dataservice/consumer_detach", job=job.name,
                        consumer=consumer, splits=moved)
                    self._consumer_seen.pop((job.name, consumer), None)
                    self.send(sock, {"type": "OK", "moved": moved})
            elif mtype == "WORKERS":
                self.send(sock, {"type": "WORKERS",
                                 "data": sorted(self._workers.values(),
                                                key=lambda m: m["worker_id"])})
            elif mtype == "KNOB":
                # queue a {knob: value} update for the worker fleet (or one
                # worker_id); delivery rides the next HBEAT replies.  Sent
                # by ServiceFeed.apply_knob relaying autopilot pushes.
                knobs = data.get("knobs")
                if not isinstance(knobs, dict) or not knobs:
                    self.send(sock, {"type": "ERR",
                                     "error": "KNOB without a knobs dict"})
                else:
                    seq = self.knobs.push(knobs,
                                          executor_id=data.get("worker_id"))
                    telemetry.get_tracer().instant(
                        "dataservice/knob", knobs=",".join(sorted(knobs)),
                        seq=seq)
                    self.send(sock, {"type": "OK", "seq": seq})
            elif mtype == "TASK":
                job = self._jobs.get(data.get("job"))
                worker_id = data.get("worker_id")
                consumer_id = data.get("consumer_id")
                if job is None:
                    self.send(sock, {"type": "ERR",
                                     "error": "unknown job {!r}"
                                              .format(data.get("job"))})
                elif worker_id in self._dead:
                    # a fenced-but-alive zombie must stop serving: its
                    # splits were re-pooled, streaming on would only feed
                    # the consumer-side dedupe
                    self.send(sock, {"type": "ERR",
                                     "error": "marked dead by the liveness "
                                              "monitor"})
                elif consumer_id in job.fenced_consumers:
                    self.send(sock, {"type": "ERR",
                                     "error": "consumer {} of job {!r} was "
                                              "fenced by the liveness "
                                              "monitor".format(
                                                  consumer_id, job.name)})
                elif job.error is not None:
                    self.send(sock, {"type": "ERR",
                                     "error": "job {!r} failed: {}".format(
                                         job.name, job.error)})
                else:
                    self._touch_consumer(job, consumer_id)
                    ans = job.next_splits(worker_id, consumer_id,
                                          list(self._workers),
                                          worker_caches=self._worker_cache,
                                          affinity=self.affinity)
                    ans["type"] = "TASK"
                    if ans.get("splits") and job.mode != SHARD_OFF:
                        for s, _path in ans["splits"]:
                            self._journal({"t": "assign", "job": job.name,
                                           "epoch": job.epoch, "split": s,
                                           "worker": worker_id,
                                           "consumer": consumer_id})
                    if ans.get("splits"):
                        # Trace flow: a fresh id rides the assignment to the
                        # worker, the stream frames, and the consumer commit,
                        # so Perfetto links assignment -> serve -> commit ->
                        # infeed -> dispatch causally across processes.
                        tracer = telemetry.get_tracer()
                        fid = tracer.new_flow_id()
                        if fid:
                            ans["flow"] = fid
                            tracer.flow_start(
                                "dataservice/split_flow", fid,
                                job=job.name, worker_id=worker_id,
                                splits=list(ans["splits"]),
                                epoch=ans.get("epoch"))
                    self.send(sock, ans)
            elif mtype == "LOST":
                job = self._jobs.get(data.get("job"))
                if job is None:
                    self.send(sock, {"type": "ERR",
                                     "error": "unknown job {!r}"
                                              .format(data.get("job"))})
                else:
                    self._touch_consumer(job, data.get("consumer_id"))
                    ans = job.release_split(int(data.get("epoch", 0)),
                                            int(data.get("split", -1)),
                                            data.get("worker_id"),
                                            data.get("consumer_id"))
                    if not ans.get("stale"):
                        self._journal({"t": "repool", "job": job.name,
                                       "epoch": int(data.get("epoch", 0)),
                                       "split": int(data.get("split", -1)),
                                       "consumer": data.get("consumer_id")})
                        logger.warning(
                            "dataservice: split %s of job %r re-pooled "
                            "after a broken stream to worker %s",
                            data.get("split"), job.name,
                            data.get("worker_id"))
                        telemetry.get_tracer().instant(
                            "dataservice/split_lost", job=job.name,
                            split=int(data.get("split", -1)),
                            worker_id=data.get("worker_id"))
                    ans["type"] = "OK"
                    self.send(sock, ans)
            elif mtype == "SPLIT_ERR":
                job = self._jobs.get(data.get("job"))
                if job is None:
                    self.send(sock, {"type": "ERR",
                                     "error": "unknown job {!r}"
                                              .format(data.get("job"))})
                else:
                    ans = job.record_split_error(
                        int(data.get("epoch", 0)),
                        int(data.get("split", -1)),
                        data.get("worker_id"), data.get("consumer_id"),
                        data.get("error") or "reader failure")
                    if not ans.get("stale"):
                        self._journal({
                            "t": "split_err", "job": job.name,
                            "epoch": int(data.get("epoch", 0)),
                            "split": int(data.get("split", -1)),
                            "worker": data.get("worker_id"),
                            "consumer": data.get("consumer_id"),
                            "error": data.get("error") or "reader failure"})
                    if ans.get("failed"):
                        logger.error("dataservice: job %r failed: %s",
                                     job.name, job.error)
                        telemetry.get_tracer().instant(
                            "dataservice/job_failed", job=job.name,
                            error=job.error)
                    ans["type"] = "OK"
                    self.send(sock, ans)
            elif mtype == "DONE":
                job = self._jobs.get(data.get("job"))
                if job is None:
                    self.send(sock, {"type": "ERR",
                                     "error": "unknown job {!r}"
                                              .format(data.get("job"))})
                elif data.get("consumer_id") in job.fenced_consumers:
                    # the fresh-identity rule for consumers: a fenced-but-
                    # alive run's parked DONEs must not land after its
                    # splits were rebound (the co-consumer republish race)
                    self.send(sock, {"type": "ERR",
                                     "error": "consumer {} of job {!r} was "
                                              "fenced by the liveness "
                                              "monitor".format(
                                                  data.get("consumer_id"),
                                                  job.name)})
                else:
                    self._touch_consumer(job, data.get("consumer_id"))
                    ans = job.complete(int(data.get("epoch", 0)),
                                       int(data.get("split", -1)),
                                       data.get("consumer_id"))
                    if not (ans.get("stale") or ans.get("duplicate")):
                        self._journal({"t": "done", "job": job.name,
                                       "epoch": int(data.get("epoch", 0)),
                                       "split": int(data.get("split", -1)),
                                       "consumer": data.get("consumer_id")})
                    if job.done:
                        telemetry.get_tracer().instant(
                            "dataservice/job_done", job=job.name)
                    ans["type"] = "OK"
                    self.send(sock, ans)
            elif mtype == "STATUS":
                job = self._jobs.get(data.get("job"))
                if job is None:
                    self.send(sock, {"type": "ERR",
                                     "error": "unknown job {!r}"
                                              .format(data.get("job"))})
                elif data.get("consumer_id") in job.fenced_consumers:
                    self.send(sock, {"type": "ERR",
                                     "error": "consumer {} of job {!r} was "
                                              "fenced by the liveness "
                                              "monitor".format(
                                                  data.get("consumer_id"),
                                                  job.name)})
                else:
                    self._touch_consumer(job, data.get("consumer_id"))
                    status = job.status()
                    status["workers"] = len(self._workers)
                    status["dead_workers"] = len(self._dead)
                    self.send(sock, {"type": "STATUS", "data": status})
            elif mtype == "STOP":
                self.send(sock, {"type": "OK"})
                self._stopping = True
            else:
                logger.warning("dataservice: ignoring unknown message %r",
                               mtype)
                self.send(sock, {"type": "ERR",
                                 "error": "unknown message type"})
        return True

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        """Bind, recover the ledger from the journal (when armed), spawn
        the daemon listener thread, return ``(host, port)``."""
        self._socket = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._socket.bind(("", self._port))
        self._socket.listen(64)
        if self.journal_dir:
            with self._lock:
                # Claim the ledger BEFORE recovering: the epoch bump fences
                # any prior incarnation (restart-in-place or the primary a
                # standby is superseding) out of the journal.
                self.fencing_epoch = standby_mod.advance_epoch(
                    self.journal_dir)
                self._recover()
        host = self._host
        if not host:
            from tensorflowonspark_tpu import util

            host = util.get_ip_address()
        addr = (host, self._socket.getsockname()[1])
        if self.journal_dir:
            self._stamp_beacon(addr, force=True)

        def _listen():
            conns = [self._socket]
            while not self._stopping:
                try:
                    readable, _, _ = select.select(conns, [], [], 0.1)
                except (OSError, ValueError):
                    break  # listen socket closed by stop()
                for sock in readable:
                    if sock is self._socket:
                        try:
                            client, _ = sock.accept()
                        except OSError:
                            continue
                        conns.append(client)
                        continue
                    try:
                        keep = self._handle_message(sock, self.receive(sock))
                    except (EOFError, OSError, ValueError):
                        keep = False
                    if not keep:
                        conns.remove(sock)
                        sock.close()
                self._check_liveness()
                self._stamp_beacon(addr)
            for sock in conns:
                try:
                    sock.close()
                except OSError:
                    pass

        self._thread = threading.Thread(target=_listen,
                                        name="dataservice-dispatcher",
                                        daemon=True)
        self._thread.start()
        logger.info("dataservice dispatcher listening on %s:%d",
                    addr[0], addr[1])
        return addr

    def stop(self):
        self._stopping = True
        if self._socket is not None:
            # shutdown() before close(): the listener's select() holds a
            # kernel reference to the listen socket, and a bare close()
            # leaves the port accepting-then-resetting for up to one poll
            # timeout — a failing-over client would waste a dial on it.
            try:
                self._socket.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._socket.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5)
        with self._lock:
            if self._journal_file is not None:
                try:
                    self._journal_file.close()
                except OSError:
                    pass
                self._journal_file = None


# ---------------------------------------------------------------------------
# DispatcherClient
# ---------------------------------------------------------------------------

class DispatcherClient(Client):
    """Typed request helpers over the rendezvous ``Client`` transport
    (connect retries, finite request timeouts, ``HBEAT``/``BYE`` reuse)."""

    # The dispatcher protocol uses "epoch" for the job DATA epoch, so its
    # fencing epoch rides a separate key (see DispatcherServer.send).
    _fence_epoch_key = "fence_epoch"

    def _call(self, mtype, data=None):
        resp = self._request({"type": mtype, "data": data or {}})
        if resp.get("type") == "ERR":
            raise DispatchError(resp.get("error", "dispatcher error"))
        return resp

    def register_worker(self, worker_id, host, port, cache_splits=None):
        data = {"worker_id": worker_id, "host": host, "port": int(port)}
        if cache_splits is not None:
            # the affinity advertisement: source paths this worker's chunk
            # cache can replay (kept fresh by the heartbeat metrics)
            data["cache_splits"] = list(cache_splits)
        self._call("WREG", data)

    def register_job(self, name, splits=None, num_epochs=1,
                     mode=SHARD_DYNAMIC, consumer_id=None, attach="auto"):
        """Attach-or-create a dataset job.

        ``attach="auto"`` (default) creates the job when absent and
        attaches to it otherwise; ``attach=False`` refuses an existing
        job; ``attach=True`` refuses a missing one — and then ``splits``
        may be ``None``, adopting the live job's spec from the reply.
        Returns the dispatcher's answer:
        ``{"created", "spec", "epoch", "done", "consumers"}``.  An
        existing job with an incompatible spec (different splits, epochs
        or mode) raises :class:`DispatchError`."""
        data = {"name": name, "num_epochs": num_epochs, "mode": mode,
                "attach": {True: "attach", False: "create"}.get(
                    attach, "auto")}
        if splits is not None:
            data["splits"] = list(splits)
        if consumer_id:
            data["consumer_id"] = consumer_id
        resp = self._call("JOB", data)
        return {k: resp.get(k)
                for k in ("created", "spec", "epoch", "done", "consumers")}

    def detach_job(self, name, consumer_id):
        """Detach a consumer: its bound splits rebind to the survivors."""
        return self._call("DETACH", {"job": name,
                                     "consumer_id": consumer_id})

    def push_knobs(self, knobs, worker_id=None):
        """Queue a live-knob ``{name: value}`` update for the worker fleet
        (or one ``worker_id``); delivery rides the workers' next heartbeat
        replies exactly-once (see docs/AUTOPILOT.md)."""
        data = {"knobs": dict(knobs)}
        if worker_id is not None:
            data["worker_id"] = worker_id
        return self._call("KNOB", data).get("seq")

    def workers(self):
        """Live worker roster as a list of ``{worker_id, host, port}``."""
        return self._call("WORKERS").get("data") or []

    def request_task(self, job, worker_id, consumer_id):
        return self._call("TASK", {"job": job, "worker_id": worker_id,
                                   "consumer_id": consumer_id})

    def done_split(self, job, epoch, split, consumer_id):
        return self._call("DONE", {"job": job, "epoch": epoch,
                                   "split": split,
                                   "consumer_id": consumer_id})

    def lost_split(self, job, epoch, split, worker_id, consumer_id):
        """Report a broken worker→consumer stream: the dispatcher re-pools
        the mid-flight split immediately (no fence wait)."""
        return self._call("LOST", {"job": job, "epoch": epoch,
                                   "split": split, "worker_id": worker_id,
                                   "consumer_id": consumer_id})

    def split_error(self, job, epoch, split, worker_id, consumer_id, error):
        """Report a worker-side reader fault on a split."""
        return self._call("SPLIT_ERR", {"job": job, "epoch": epoch,
                                        "split": split,
                                        "worker_id": worker_id,
                                        "consumer_id": consumer_id,
                                        "error": error})

    def status(self, job, consumer_id=None):
        data = {"job": job}
        if consumer_id:
            # names the caller so the dispatcher's consumer-liveness clock
            # refreshes on every poll (and a fenced consumer learns loudly)
            data["consumer_id"] = consumer_id
        return self._call("STATUS", data).get("data") or {}


def _default_retry_policy():
    # dial/registration races at service bring-up are connection-shaped and
    # resolve in well under a second on localhost
    return fault.RetryPolicy(max_attempts=4, initial_backoff=0.1,
                             max_backoff=1.0)


# ---------------------------------------------------------------------------
# Worker-side chunk cache
# ---------------------------------------------------------------------------

def _env_cache_bytes():
    raw = os.environ.get("TFOS_DS_CACHE_BYTES", "").strip()
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        logger.warning("ignoring non-integer TFOS_DS_CACHE_BYTES=%r", raw)
        return None


# Spill-file frame record: kind (u8), item count (u32), payload length (u64).
_SPILL_REC = struct.Struct("<BIQ")


class _FrameCache(object):
    """Byte-budgeted LRU of serialized split streams (the tf.data-service
    paper's source cache, at the worker).

    The unit of caching is the exact ``(kind, payload, items)`` frame
    sequence a cold serve produced for one split — colv1 frames
    *post-compression*, pickle-fallback frames included — so an epoch ≥ 2
    (or post-re-pool) serve replays bytes without touching ``FileFeed``,
    the row decoder, or the wire codec.  Entries are keyed by the split's
    source identity ``(path, wire codec)``, which subsumes (job
    signature, split index): a worker's serialized frames depend only on
    the file's content and the negotiated codec, so two jobs over the
    same dataset share entries while different datasets never collide.
    Every lookup re-validates the source file's ``(size, mtime_ns)``
    captured when the cold read *started*; a touched/resized source drops
    the entry (tallied as an invalidation) and the split is re-decoded.

    Overflow: LRU over resident bytes.  With ``spill_dir`` set, evicted
    entries spill to disk under it (their own LRU, ``spill_budget``
    bytes, default 4× the memory budget) and a spill hit promotes the
    entry back to memory; without it they are dropped.  All bookkeeping
    sits behind one lock — serve streams are concurrent, frame lists are
    immutable once inserted.
    """

    def __init__(self, max_bytes, spill_dir=None, spill_budget=None):
        self.max_bytes = int(max_bytes)
        self.spill_dir = spill_dir
        self.spill_budget = (int(spill_budget) if spill_budget is not None
                             else 4 * self.max_bytes)
        self._entries = collections.OrderedDict()  # key -> entry (resident)
        self._spilled = collections.OrderedDict()  # key -> entry (on disk)
        self._resident = 0
        self._spilled_bytes = 0
        self._seq = 0
        self._lock = threading.Lock()
        # tallies (read cross-thread; see FeedWorker heartbeat metrics)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.spills = 0
        self.spill_hits = 0
        self.invalidations = 0
        self.uncacheable = 0
        self.bytes_served = 0
        self.spill_bytes = 0          # cumulative bytes written to spill
        self._unreported_spill = 0    # since the last take_spill_bytes()

    @staticmethod
    def signature(path):
        """``(size, mtime_ns)`` of the source file, or ``None`` when it
        cannot be stat'ed (synthetic reader paths): such entries skip
        freshness validation and rely on LRU turnover alone."""
        try:
            st = os.stat(path)
        except (OSError, TypeError, ValueError):
            return None
        return (st.st_size, getattr(st, "st_mtime_ns", st.st_mtime))

    # -- internal (caller holds the lock) ----------------------------------

    def _drop(self, key, entry):
        self._entries.pop(key, None)
        self._spilled.pop(key, None)
        if entry.get("frames") is not None:
            self._resident -= entry["nbytes"]
        spill = entry.get("spill")
        if spill:
            self._spilled_bytes -= entry["nbytes"]
            try:
                os.unlink(spill)
            except OSError:
                pass

    def _spill_entry(self, key, entry):
        """Move a resident entry to disk; False when spill is off/fails."""
        if (self.spill_dir is None
                or entry["nbytes"] > self.spill_budget):
            return False
        path = os.path.join(self.spill_dir,
                            "split-{:08d}.cache".format(self._seq))
        self._seq += 1
        try:
            os.makedirs(self.spill_dir, exist_ok=True)
            with open(path, "wb") as f:
                for kind, payload, items in entry["frames"]:
                    f.write(_SPILL_REC.pack(kind, items, len(payload)))
                    f.write(payload)
        except OSError as e:
            logger.warning("chunk cache: spill of %r failed (%s)",
                           entry["path"], e)
            try:
                os.unlink(path)
            except OSError:
                pass
            return False
        entry["frames"] = None
        entry["spill"] = path
        self._spilled[key] = entry
        self._spilled_bytes += entry["nbytes"]
        self.spill_bytes += entry["nbytes"]
        self._unreported_spill += entry["nbytes"]
        while self._spilled_bytes > self.spill_budget and self._spilled:
            old_key, old = self._spilled.popitem(last=False)
            self._drop(old_key, old)
        return True

    def _load_spill(self, entry):
        """Frames list read back from an entry's spill file, or ``None``."""
        try:
            with open(entry["spill"], "rb") as f:
                frames = []
                while True:
                    rec = f.read(_SPILL_REC.size)
                    if not rec:
                        return frames
                    kind, items, length = _SPILL_REC.unpack(rec)
                    payload = f.read(length)
                    if len(payload) != length:
                        raise OSError("truncated spill record")
                    frames.append((kind, payload, items))
        except OSError as e:
            logger.warning("chunk cache: spill read-back of %r failed (%s)",
                           entry["path"], e)
            return None

    def _evict_overflow(self):
        while self._resident > self.max_bytes and self._entries:
            key, entry = self._entries.popitem(last=False)
            self._resident -= entry["nbytes"]
            self.evictions += 1
            if self._spill_entry(key, entry):
                self.spills += 1

    def set_max_bytes(self, max_bytes):
        """Live budget retune (autopilot ``dataservice_cache_budget``
        knob): a raise takes effect on the next insert; a shrink evicts
        down to the new budget immediately (spilling per the usual rules).
        The spill budget keeps its 4× ratio unless it was set explicitly.
        """
        max_bytes = int(max_bytes)
        with self._lock:
            grew_spill = self.spill_budget == 4 * self.max_bytes
            self.max_bytes = max_bytes
            if grew_spill:
                self.spill_budget = 4 * max_bytes
            self._evict_overflow()

    # -- serve-thread API --------------------------------------------------

    def lookup(self, path, codec):
        """The cached frame list for ``(path, codec)``, or ``None`` (miss /
        stale / unreadable spill).  A hit refreshes LRU order; a spilled
        hit is promoted back to memory first."""
        key = (path, codec or "none")
        with self._lock:
            entry = self._entries.get(key) or self._spilled.get(key)
            if entry is None:
                self.misses += 1
                return None
            if (entry["sig"] is not None
                    and self.signature(path) != entry["sig"]):
                self._drop(key, entry)
                self.invalidations += 1
                self.misses += 1
                return None
            if entry["frames"] is None:
                frames = self._load_spill(entry)
                if frames is None:
                    self._drop(key, entry)
                    self.misses += 1
                    return None
                self.spill_hits += 1
                self._spilled.pop(key, None)
                self._spilled_bytes -= entry["nbytes"]
                try:
                    os.unlink(entry["spill"])
                except OSError:
                    pass
                entry["frames"], entry["spill"] = frames, None
                self._entries[key] = entry
                self._resident += entry["nbytes"]
            self._entries.move_to_end(key)
            self._evict_overflow()
            self.hits += 1
            self.bytes_served += entry["nbytes"]
            return entry["frames"]

    def put(self, path, codec, sig, frames):
        """Insert a completely-served split's frames (``sig`` captured
        before the cold read started).  Returns how many entries this
        insert pushed out of memory — the per-stream eviction delta the
        worker reports on ``split_end``."""
        nbytes = sum(len(p) for _, p, _ in frames)
        key = (path, codec or "none")
        with self._lock:
            old = self._entries.get(key) or self._spilled.get(key)
            if old is not None:
                self._drop(key, old)
            if nbytes > self.max_bytes:
                self.uncacheable += 1
                return 0
            before = self.evictions
            self._entries[key] = {"path": path, "sig": sig,
                                  "frames": list(frames), "nbytes": nbytes,
                                  "spill": None}
            self._resident += nbytes
            self._evict_overflow()
            return self.evictions - before

    # -- observability -----------------------------------------------------

    def resident_bytes(self):
        with self._lock:
            return self._resident

    def take_spill_bytes(self):
        """Spill bytes written since the last call (atomic take-and-reset;
        the per-stream delta a worker rides on ``split_end`` — conserved
        across concurrent serve streams)."""
        with self._lock:
            n, self._unreported_spill = self._unreported_spill, 0
            return n

    def cached_paths(self):
        """Source paths with a resident or spilled entry — the affinity
        advertisement this worker rides on WREG and every heartbeat."""
        with self._lock:
            paths = {k[0] for k in self._entries}
            paths.update(k[0] for k in self._spilled)
            return sorted(paths)

    def counters_flat(self):
        """The ``dataservice_cache_*`` heartbeat vocabulary (``_max``
        suffix = gauge, everything else cumulative counters)."""
        with self._lock:
            return {"dataservice_cache_hit": self.hits,
                    "dataservice_cache_miss": self.misses,
                    "dataservice_cache_bytes": self.bytes_served,
                    "dataservice_cache_evictions": self.evictions,
                    "dataservice_cache_spills": self.spills,
                    "dataservice_cache_spill_hits": self.spill_hits,
                    "dataservice_cache_spill_bytes": self.spill_bytes,
                    "dataservice_cache_invalidations": self.invalidations,
                    "dataservice_cache_resident_max": self._resident}


# ---------------------------------------------------------------------------
# FeedWorker
# ---------------------------------------------------------------------------

class FeedWorker(object):
    """One data-service worker: reads splits, streams framed blocks.

    Listens on ``(host, port)`` for consumer streams; each accepted stream
    sends a JSON hello ``{"job", "consumer"}`` and then receives splits as
    the worker wins them from the dispatcher (``TASK`` poll per stream).
    Rows come from a per-split :class:`~tensorflowonspark_tpu.data.FileFeed`
    (or :class:`~tensorflowonspark_tpu.data.ProcessPoolFeed` with
    ``use_process_pool=True``) built over ``row_reader``; blocks go out as
    colv1 frames when framable, pickled rows otherwise — exactly the
    ``node._ChunkPutter`` fallback rules, including the
    ``TFOS_WIRE_FORMAT=pickle`` A/B knob.

    Liveness: a ``HeartbeatSender`` pointed at the dispatcher (the
    ``HBEAT``/``BYE`` wire shapes are shared with the rendezvous) carrying
    the worker's cache/compression counters as its piggybacked metrics.
    Chaos: ``fault.FaultInjector`` hooks fire per block
    (``kill_after_items``) and per finished split (``kill_after_splits``)
    — on cached replays too, so chaos coverage survives the cache.

    ``cache_bytes`` arms the worker chunk cache (:class:`_FrameCache`):
    the serialized frames of each completely-served split are kept under
    a byte-budgeted LRU and replayed on later serves of the same source
    (epoch ≥ 2, or a re-pooled split landing back on this worker),
    skipping the reader and codec entirely.  ``None`` reads
    ``TFOS_DS_CACHE_BYTES``; 0/unset disables.  ``cache_spill_dir``
    additionally spills evicted entries to disk under the worker's work
    dir.
    """

    def __init__(self, dispatcher_addr, row_reader=None, host="127.0.0.1",
                 port=0, worker_id=None, heartbeat_interval=1.0,
                 use_process_pool=False, num_procs=2, retry_policy=None,
                 cache_bytes=None, cache_spill_dir=None,
                 advertise_cache=None):
        # Endpoint-list discovery: entry 0 the primary dispatcher, later
        # entries warm standbys at pinned ports; DispatcherClient redials
        # across the list, so a worker survives a dispatcher failover.
        self.dispatcher_endpoints = normalize_endpoints(dispatcher_addr)
        self.dispatcher_addr = self.dispatcher_endpoints[0]
        self.row_reader = row_reader
        self.host = host
        self.port = port
        self.worker_id = worker_id or "worker-{}-{}".format(
            socket.gethostname(), id(self) & 0xffffff)
        self.heartbeat_interval = heartbeat_interval
        self.use_process_pool = use_process_pool
        self.num_procs = num_procs
        self.retry_policy = retry_policy or _default_retry_policy()
        # telemetry/test tallies (plain ints; read cross-thread)
        self.splits_streamed = 0
        self.items_streamed = 0
        self.bytes_streamed = 0
        if cache_bytes is None:
            cache_bytes = _env_cache_bytes()
        self.chunk_cache = (_FrameCache(cache_bytes,
                                        spill_dir=cache_spill_dir)
                            if cache_bytes else None)
        if advertise_cache is None:
            advertise_cache = _env_flag("TFOS_DS_ADVERTISE", True)
        # the affinity advertisement only exists when there is a cache to
        # advertise; --no-cache-advertise is the scheduler A/B knob
        self.advertise_cache = bool(advertise_cache) and (
            self.chunk_cache is not None)
        self._last_rereg = 0.0
        # producer-side wire-compression accounting, incremented in place
        # by wire.frame_bytes (raw_bytes / wire_bytes / cols_* / frames)
        self.compress_stats = {}
        self._framed = wire.enabled()
        self._injector = fault.from_env()
        self._stop = threading.Event()
        self._socket = None
        self._heartbeat = None
        self._accept_thread = None
        self._conns = set()
        self._conns_lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        """Bind the data port, register with the dispatcher, start
        heartbeating and accepting consumer streams.  Returns self."""
        self._socket = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._socket.bind((self.host, self.port))
        self._socket.listen(16)
        self.port = self._socket.getsockname()[1]

        def _register():
            client = DispatcherClient(self.dispatcher_endpoints)
            try:
                client.register_worker(
                    self.worker_id, self.host, self.port,
                    cache_splits=(self.chunk_cache.cached_paths()
                                  if self.advertise_cache else None))
            finally:
                client.close()

        self.retry_policy.call(_register)
        self._heartbeat = HeartbeatSender(
            self.dispatcher_endpoints, self.worker_id,
            self.heartbeat_interval,
            metrics_provider=self._heartbeat_metrics,
            on_reply=self._on_beat_reply).start()
        self._accept_thread = threading.Thread(
            target=self._accept_loop,
            name="feedworker-accept-{}".format(self.worker_id), daemon=True)
        self._accept_thread.start()
        logger.info("feed worker %s serving on %s:%d", self.worker_id,
                    self.host, self.port)
        return self

    def stop(self, abrupt=False):
        """Shut down.  ``abrupt=True`` models a crash for tests: streams and
        heartbeats just stop (no ``BYE``), so the dispatcher must fence this
        worker by heartbeat timeout and re-pool its splits."""
        self._stop.set()
        if self._socket is not None:
            try:
                self._socket.close()
            except OSError:
                pass
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        if self._heartbeat is not None:
            self._heartbeat.stop(goodbye=not abrupt)

    def _on_beat_reply(self, resp):
        """A heartbeat answer carrying ``reregister`` means the dispatcher
        restarted and has never seen this worker: re-send WREG (throttled
        to one attempt per heartbeat interval; best-effort — the next beat
        retries).  A ``knobs`` dict is a live-knob push relayed through
        the dispatcher (autopilot ``dataservice_cache_budget``): applied
        inline — a budget retune is a bounded eviction pass.  Runs on the
        heartbeat thread."""
        knobs = resp.get("knobs")
        if isinstance(knobs, dict):
            budget = knobs.get("dataservice_cache_budget")
            if budget is not None and self.chunk_cache is not None:
                try:
                    self.chunk_cache.set_max_bytes(budget)
                    logger.info("feed worker %s: cache budget retuned to "
                                "%d bytes", self.worker_id, int(budget))
                except Exception:
                    logger.warning("feed worker %s: cache budget knob "
                                   "failed", self.worker_id, exc_info=True)
        if not resp.get("reregister") or self._stop.is_set():
            return
        now = time.monotonic()
        if now - self._last_rereg < self.heartbeat_interval:
            return
        self._last_rereg = now
        try:
            client = DispatcherClient(self.dispatcher_endpoints, retries=0)
            try:
                client.register_worker(
                    self.worker_id, self.host, self.port,
                    cache_splits=(self.chunk_cache.cached_paths()
                                  if self.advertise_cache else None))
            finally:
                client.close()
            logger.info("feed worker %s: re-registered with a restarted "
                        "dispatcher", self.worker_id)
        except DispatchError as e:
            # e.g. a racing beat already re-registered us
            logger.debug("feed worker %s: re-registration refused (%s)",
                         self.worker_id, e)
        except Exception as e:
            logger.warning("feed worker %s: re-registration failed (%s)",
                           self.worker_id, e)

    # -- stream serving ----------------------------------------------------

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                readable, _, _ = select.select([self._socket], [], [], 0.2)
            except (OSError, ValueError):
                return
            if not readable:
                continue
            try:
                conn, _ = self._socket.accept()
            except OSError:
                return
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve_stream, args=(conn,),
                             name="feedworker-stream-{}".format(
                                 self.worker_id),
                             daemon=True).start()

    def _serve_stream(self, conn):
        client = None
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            kind, payload = _recv_frame(conn)
            if kind != _K_JSON:
                raise DispatchError("stream hello must be a JSON frame")
            hello = json.loads(payload)
            job, consumer = hello["job"], hello["consumer"]
            # Dial-time codec negotiation: the consumer's hello offers its
            # codec names in preference order; the first one this worker
            # supports compresses every colv1 frame on this stream (column-
            # wise, pay-off sampled).  A hello without "codecs" — an older
            # consumer — gets raw frames, byte-identical to before.
            codec = wire.negotiate_codec(hello.get("codecs"))
            client = DispatcherClient(self.dispatcher_endpoints)
            while not self._stop.is_set():
                task = client.request_task(job, self.worker_id, consumer)
                if task.get("wait"):
                    time.sleep(0.05)
                    continue
                if task.get("done"):
                    _send_json(conn, {"type": "stream_end"})
                    break
                for _ in range(int(task.get("epochs", 1))):
                    for split, path in task["splits"]:
                        self._stream_split(conn, client, job, consumer,
                                           split, int(task.get("epoch", 0)),
                                           path, flow=task.get("flow"),
                                           codec=codec)
        except (EOFError, OSError) as e:
            logger.info("feed worker %s: stream closed (%s)",
                        self.worker_id, e)
        except DispatchError as e:
            # fenced mid-serve, or the job vanished: end the stream; the
            # consumer's partial-split discard handles the rest
            logger.warning("feed worker %s: dispatcher refused (%s)",
                           self.worker_id, e)
        except Exception:
            if not self._stop.is_set():
                logger.exception("feed worker %s: stream failed",
                                 self.worker_id)
        finally:
            if client is not None:
                client.close()
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _make_feed(self, path):
        from tensorflowonspark_tpu import data

        if self.use_process_pool:
            return data.ProcessPoolFeed([path], row_reader=self.row_reader,
                                        num_procs=self.num_procs, shard=False)
        return data.FileFeed([path], row_reader=self.row_reader,
                             reader_threads=1, shard=False)

    def _stream_split(self, conn, client, job, consumer, split, epoch, path,
                      flow=None, codec=None):
        # Reader faults (unreadable file, bad records) are kept separate
        # from socket faults: the reader calls sit in their own try so an
        # OSError from the filesystem is never mistaken for a dead stream.
        tracer = telemetry.get_tracer()
        if flow:
            # flow ids ride the stream's control frames so the consumer can
            # continue the dispatcher-started trace flow across processes
            tracer.flow_step("dataservice/split_flow", flow,
                             leg="worker_serve", split=split,
                             worker_id=self.worker_id)
        cached = (self.chunk_cache.lookup(path, codec)
                  if self.chunk_cache is not None else None)
        with tracer.span("dataservice/split_stream", split=split,
                         epoch=epoch, worker_id=self.worker_id,
                         cache="hit" if cached is not None else "miss"):
            begin = {"type": "split_begin", "split": split, "epoch": epoch}
            end = {"type": "split_end", "split": split, "epoch": epoch}
            if flow:
                begin["flow"] = end["flow"] = flow
            if codec:
                begin["codec"] = codec
            if self.chunk_cache is not None:
                # the serve verdict rides both control frames so consumers
                # tally dataservice_cache_* without a second channel
                begin["cache"] = end["cache"] = (
                    "hit" if cached is not None else "miss")
            _send_json(conn, begin)
            if cached is not None:
                # replay the serialized frames: no FileFeed, no decode, no
                # codec work — chaos hooks still fire per block/split
                served = 0
                for kind, payload, items in cached:
                    if self._stop.is_set():
                        break
                    _send_frame(conn, kind, payload)
                    self.items_streamed += items
                    self.bytes_streamed += len(payload)
                    served += len(payload)
                    self._injector.on_items(items)
                end["cache_bytes"] = served
            else:
                fill = [] if self.chunk_cache is not None else None
                # freshness signature is captured BEFORE the read starts:
                # a file mutated mid-read mismatches at the next lookup
                sig = (_FrameCache.signature(path) if fill is not None
                       else None)
                feed = None
                complete = False
                try:
                    try:
                        feed = self._make_feed(path)
                        feed._ensure_started()
                    except Exception as e:
                        self._abort_split(conn, client, job, consumer, split,
                                          epoch, e)
                        return
                    while not self._stop.is_set():
                        try:
                            block = feed._next_rows()
                        except Exception as e:
                            self._abort_split(conn, client, job, consumer,
                                              split, epoch, e)
                            return
                        if block is None:
                            complete = True
                            break
                        self._send_block(conn, block, codec=codec,
                                         record=fill)
                finally:
                    if feed is not None:
                        feed.terminate()
                if fill is not None and complete:
                    evicted = self.chunk_cache.put(path, codec, sig, fill)
                    if evicted:
                        end["cache_evicted"] = evicted
            if self.chunk_cache is not None:
                end["cache_resident"] = self.chunk_cache.resident_bytes()
                spilled = self.chunk_cache.take_spill_bytes()
                if spilled:
                    end["cache_spill_bytes"] = spilled
            _send_json(conn, end)
        self.splits_streamed += 1
        self._injector.on_split()

    def _abort_split(self, conn, client, job, consumer, split, epoch, exc):
        """In-band recovery from a reader fault: the stream is healthy, so
        tell the consumer to drop the partial buffer (``split_abort``) and
        the dispatcher to re-pool or fail the split (``SPLIT_ERR``) — the
        alternative, letting the exception kill the stream, would leave
        the split assigned to a live worker forever with no diagnosis."""
        desc = "{}: {}".format(type(exc).__name__, exc)
        logger.warning("feed worker %s: split %s of job %r failed to read "
                       "(%s)", self.worker_id, split, job, desc)
        telemetry.get_tracer().instant(
            "dataservice/split_error", worker_id=self.worker_id,
            split=split, error=desc)
        _send_json(conn, {"type": "split_abort", "split": split,
                          "epoch": epoch, "error": desc})
        try:
            client.split_error(job, epoch, split, self.worker_id, consumer,
                               desc)
        except DispatchError as e:
            logger.warning("feed worker %s: SPLIT_ERR refused (%s)",
                           self.worker_id, e)

    def _send_block(self, conn, block, codec=None, record=None):
        payload = None
        kind = _K_PICKLE
        if self._framed:
            chunk = marker.pack_columnar(block)
            if chunk is not None:
                payload = wire.frame_chunk_bytes(chunk, codec=codec,
                                                 stats=self.compress_stats)
                kind = _K_COLV1
        if payload is None:
            payload = pickle.dumps(block, protocol=pickle.HIGHEST_PROTOCOL)
            kind = _K_PICKLE
        _send_frame(conn, kind, payload)
        if record is not None:
            # the exact wire form (kind + serialized payload) is what the
            # cache replays, so hits skip pack/frame/compress entirely
            record.append((kind, payload, len(block)))
        self.items_streamed += len(block)
        self.bytes_streamed += len(payload)
        self._injector.on_items(len(block))

    def _heartbeat_metrics(self):
        """Counter snapshot riding worker HBEATs to the dispatcher (which
        latches the latest per worker for ``worker_metrics()``)."""
        out = {
            "dataservice_worker_splits": self.splits_streamed,
            "dataservice_worker_items": self.items_streamed,
            "dataservice_worker_bytes": self.bytes_streamed,
        }
        if self.chunk_cache is not None:
            out.update(self.chunk_cache.counters_flat())
            if self.advertise_cache:
                # not a counter: the dispatcher strips this path list off
                # before latching the numeric metrics
                out["cache_paths"] = self.chunk_cache.cached_paths()
        stats = self.compress_stats
        if stats.get("frames"):
            out["wire_compress_raw_bytes"] = int(stats.get("raw_bytes", 0))
            out["wire_compress_wire_bytes"] = int(stats.get("wire_bytes", 0))
        return out


# ---------------------------------------------------------------------------
# ServiceFeed
# ---------------------------------------------------------------------------

def _resolve_codecs(codecs):
    """Normalize a ``ServiceFeed(codecs=...)`` argument into the offer list
    sent in the dial hello.  ``None`` defers to ``TFOS_WIRE_CODEC`` and then
    to every codec this host supports; an explicit list is validated but
    passed through (the worker drops names it can't honour)."""
    if codecs is None:
        env = os.environ.get("TFOS_WIRE_CODEC", "").strip()
        if env:
            if env.lower() in ("off", "0", "none", "pickle"):
                return []
            if not wire.codec_supported(env):
                logger.warning("TFOS_WIRE_CODEC=%r is not supported on this "
                               "host; offering no codecs", env)
                return []
            return [env]
        return [c for c in wire.supported_codecs() if c != "none"]
    out = []
    for name in codecs:
        if not wire.codec_supported(name):
            raise ValueError("unsupported wire codec {!r} (supported: {})"
                             .format(name, wire.supported_codecs()))
        if name != "none":
            out.append(name)
    return out


class ServiceFeed(object):
    """Consumer-side client: a ``DataFeed``-compatible feed whose rows come
    from the data service instead of local files.

    Drop-in for the ``DataFeed`` duck type: ``next_batch`` /
    ``next_batch_arrays`` / ``should_stop`` / ``interrupt`` / ``terminate``
    / ``wire_formats`` / ``counters_snapshot`` — so
    ``parallel.infeed.ShardedFeed`` and ``train.fit_supervised`` consume it
    unchanged (``TPUNodeContext.get_service_feed`` is the node-side
    constructor).

    One receiver thread per worker stream decodes frames ahead of
    consumption into a bounded chunk queue — the client-side double
    buffering: the network receive of chunk N+1 overlaps the trainer's
    consumption of chunk N, ``prefetch`` chunks deep.  A maintainer thread
    tracks the dispatcher's worker roster, dialing workers as they appear
    (late joiners included) and detecting job completion.

    Shared jobs: several runs naming the same ``job_name`` attach to ONE
    ledger and split the read — each split streams to exactly one of the
    attached consumers.  ``attach`` controls the registration stance:
    ``"auto"`` (default) creates the job when absent and attaches
    otherwise; ``True`` requires a live job (``files`` may then be
    ``None`` — the live job's spec is adopted); ``False`` requires to be
    first.  A consumer that terminates early detaches so its in-flight
    splits rebind to the co-consumers; one that crashes silently is
    fenced by the dispatcher after the heartbeat deadline.

    Args:
      dispatcher_addr: ``(host, port)`` or ``"host:port"``.
      files: split paths (the job's dataset; every consumer of a job must
        pass the same list — job registration is attach-or-create).
        ``None`` is allowed with ``attach=True`` only.
      job_name: dataset job identity shared by all its consumers.
      attach: ``"auto"`` | ``True`` | ``False`` (see above).
      mode: :data:`SHARD_OFF` / :data:`SHARD_STATIC` / :data:`SHARD_DYNAMIC`.
      num_epochs: passes over the splits (epoch boundaries are invisible,
        like ``FileFeed``).
      consumer_id: this consumer's identity in the split ledger (defaults
        to ``host-pid``).
      input_mapping: as ``DataFeed`` — ``{column: tensor}``; ``next_batch``
        then returns per-tensor dicts (tuple rows only).
      prefetch: chunk-queue depth (≥2: double buffering).
      min_workers: wait for this many workers before binding (OFF mode
        binds its worker set once, see :data:`SHARD_OFF`).
      timeout: seconds without progress before the feed raises — turns a
        dead service into an error, not a hang.  Progress is any received
        frame, any commit (duplicates included), or any ledger movement
        (a co-consumer's commits count); size it above the worst-case
        stream time of a single split.
      codecs: wire-compression preference list offered at dial (first
        codec the worker supports wins; raw colv1 when nothing matches).
        ``None`` resolves from ``TFOS_WIRE_CODEC`` (a codec name, or
        ``off``/``0``/``pickle`` to offer nothing) and falls back to
        :func:`wire.supported_codecs`; ``[]`` disables the offer.
    """

    def __init__(self, dispatcher_addr, files, job_name="default",
                 mode=SHARD_DYNAMIC, num_epochs=1, consumer_id=None,
                 input_mapping=None, prefetch=2, min_workers=1,
                 retry_policy=None, timeout=60.0, codecs=None,
                 attach="auto"):
        if mode not in _MODES:
            raise ValueError("unknown sharding mode {!r} (one of {})"
                             .format(mode, _MODES))
        if attach not in ("auto", True, False):
            raise ValueError('attach must be "auto", True or False, not {!r}'
                             .format(attach))
        if files is None and attach is not True:
            raise ValueError("files=None needs attach=True (adopting the "
                             "spec of a live job)")
        # Endpoint-list discovery (primary first, standbys after): every
        # DispatcherClient below dials across the list, so the feed
        # follows a promoted standby without losing ledger state.
        self.dispatcher_endpoints = normalize_endpoints(dispatcher_addr)
        self.dispatcher_addr = self.dispatcher_endpoints[0]
        self.files = list(files) if files is not None else None
        self.attach = attach
        self.job_name = job_name
        self.mode = mode
        self.num_epochs = num_epochs
        self.consumer_id = consumer_id or "{}-{}".format(
            socket.gethostname(), id(self) & 0xffffff)
        self.input_tensors = (
            [tensor for _, tensor in sorted(input_mapping.items())]
            if input_mapping is not None else None)
        self.min_workers = min_workers
        self.retry_policy = retry_policy or _default_retry_policy()
        self.timeout = timeout
        self.codecs = _resolve_codecs(codecs)
        # DataFeed-compatible observability surface
        self.wire_formats = {}
        self.items_consumed = 0
        self.stall_secs = 0.0
        self.splits_committed = 0
        self.split_dupes = 0
        self.splits_discarded = 0
        self.bytes_received = 0
        # cache/compression telemetry relayed by workers on split_end
        self.cache_hits = 0
        self.cache_misses = 0
        self.cache_evictions = 0
        self.cache_bytes = 0
        self.cache_spill_bytes = 0
        self.compress_raw_bytes = 0
        self.compress_wire_bytes = 0
        self._cache_resident = {}   # worker_id -> latest resident gauge
        self._affinity = {}         # latest job-level affinity counters
        self.created_job = None     # True created / False attached (started)
        self._fault = fault.from_env()
        self._chunks = _queue.Queue(maxsize=max(2, prefetch))
        self._buffer = []
        self._buffer_idx = 0
        self._interrupt = threading.Event()
        self._stop = threading.Event()
        self._done = False          # sentinel consumed (consumer thread only)
        self._sentinel_sent = False
        self._errors = _queue.Queue()
        self._committed = set()     # (epoch, split) commit dedupe
        self._done_pending = set()  # committed keys whose DONE hasn't landed
        self._commit_lock = threading.Lock()
        # Trace-flow ids of recently committed splits, drained by the
        # downstream infeed/trainer (``pop_flow_id``) so the dispatcher-
        # started flow reaches the dispatch leg.  Bounded: an unobserved
        # flow just drops off (flows are best-effort diagnostics).
        self._flow_pending = collections.deque(maxlen=16)
        self._started = False
        self._streams = {}          # worker_id -> receiver thread
        self._stream_socks = {}     # worker_id -> socket
        self._stream_lock = threading.Lock()
        self._dial_failures = {}
        self._last_progress = time.monotonic()
        self._maintainer = None

    # -- service wiring ----------------------------------------------------

    def _ensure_started(self):
        if self._started:
            return
        self._started = True
        client = self.retry_policy.call(
            lambda: DispatcherClient(self.dispatcher_endpoints))
        reply = client.register_job(self.job_name, self.files,
                                    num_epochs=self.num_epochs,
                                    mode=self.mode,
                                    consumer_id=self.consumer_id,
                                    attach=self.attach)
        self.created_job = bool(reply.get("created"))
        if self.files is None:
            # attach=True without files: adopt the live job's spec (the
            # receive plane needs the mode before any stream dials)
            spec = reply.get("spec") or {}
            self.files = list(spec.get("splits") or [])
            mode = spec.get("mode", self.mode)
            if mode in _MODES:
                self.mode = mode
            self.num_epochs = spec.get("num_epochs", self.num_epochs)
        self._maintainer = threading.Thread(
            target=self._maintain, args=(client,),
            name="servicefeed-maintain-{}".format(self.consumer_id),
            daemon=True)
        self._maintainer.start()

    def _maintain(self, client):
        """Roster tracking + completion detection (daemon thread).

        The dispatcher connection is treated as replaceable: any transport
        error drops it and the next tick redials (``retries=0`` per
        attempt — the loop itself is the retry), so a dispatcher restarted
        from its journal is picked up within a tick or two.  Dispatcher
        downtime is NOT progress — the watchdog keeps running, bounding
        how long a dead control plane can stall the feed."""
        off_bound = None  # OFF mode: the worker set frozen at binding time
        last_sig = None   # last observed ledger-progress signature
        job_done = False  # normal completion (no DETACH needed)
        try:
            while not self._stop.is_set():
                if client is None:
                    try:
                        client = DispatcherClient(self.dispatcher_endpoints,
                                                  retries=0)
                    except (OSError, EOFError, TimeoutError,
                            ConnectionError) as e:
                        logger.warning("servicefeed: dispatcher unreachable "
                                       "(%s); redialing", e)
                        if (time.monotonic()
                                - self._last_progress) > self.timeout:
                            raise TimeoutError(
                                "data service made no progress for {}s "
                                "(job {!r}, dispatcher unreachable)".format(
                                    self.timeout, self.job_name))
                        time.sleep(0.2)
                        continue
                try:
                    roster = {m["worker_id"]: m for m in client.workers()}
                except DispatchError as e:
                    logger.warning("servicefeed: worker listing refused "
                                   "(%s)", e)
                    roster = {}
                except (OSError, EOFError, TimeoutError,
                        ConnectionError) as e:
                    logger.warning("servicefeed: worker listing failed (%s)",
                                   e)
                    client.close()
                    client = None
                    roster = {}
                if self.mode == SHARD_OFF:
                    if off_bound is None:
                        if len(roster) >= self.min_workers:
                            off_bound = set(roster)
                    dial = {} if off_bound is None else {
                        w: m for w, m in roster.items() if w in off_bound}
                else:
                    dial = roster
                with self._stream_lock:
                    for worker_id, meta in dial.items():
                        if (worker_id not in self._streams
                                and self._dial_failures.get(worker_id, 0) < 3):
                            t = threading.Thread(
                                target=self._receive_stream,
                                args=(worker_id, meta),
                                name="servicefeed-rx-{}".format(worker_id),
                                daemon=True)
                            self._streams[worker_id] = t
                            t.start()
                if client is not None:
                    self._flush_pending_done(client)
                # completion: ledger modes ask the dispatcher; OFF is purely
                # per-stream (all bound streams finished)
                if self.mode == SHARD_OFF:
                    with self._stream_lock:
                        threads = list(self._streams.values())
                    if (off_bound is not None and threads
                            and all(not t.is_alive() for t in threads)):
                        job_done = True
                        break
                elif client is not None:
                    status = None
                    try:
                        status = client.status(self.job_name,
                                               consumer_id=self.consumer_id)
                    except DispatchError as e:
                        if "fenced" in str(e):
                            # our identity is burnt (we went silent past
                            # the deadline and our splits were rebound):
                            # continuing would double-deliver via parked
                            # DONEs, so fail loudly instead
                            raise
                    except (OSError, EOFError, TimeoutError,
                            ConnectionError):
                        client.close()
                        client = None
                    if status is not None:
                        if status.get("error"):
                            raise DispatchError(
                                "data service job {!r} failed: {}".format(
                                    self.job_name, status["error"]))
                        if status.get("affinity_total"):
                            self._affinity = {
                                "hits": int(status.get("affinity_hits", 0)),
                                "total": int(status["affinity_total"])}
                        if status.get("done"):
                            job_done = True
                            break
                        # any ledger movement is progress: a co-consumer's
                        # commits keep this (possibly idle) consumer's
                        # watchdog quiet while the shared job advances
                        sig = (status.get("epoch"), status.get("completed"),
                               status.get("assigned"), status.get("pending"),
                               status.get("reassigned"))
                        if sig != last_sig:
                            last_sig = sig
                            self._last_progress = time.monotonic()
                if (time.monotonic() - self._last_progress) > self.timeout:
                    raise TimeoutError(
                        "data service made no progress for {}s (job {!r}, "
                        "{} worker(s) listed)".format(self.timeout,
                                                      self.job_name,
                                                      len(roster)))
                time.sleep(0.1)
            self._finish_streams()
        except Exception as e:
            self._errors.put(e)
            # error/terminate path only: delivery is already forfeit, so the
            # sentinel may evict queued chunks to land immediately
            self._publish(_SENTINEL, force=True)
        else:
            # normal completion: every committed chunk is already queued
            # (publish precedes DONE), so the sentinel queues BEHIND them —
            # a slow-draining consumer keeps its tail
            self._publish(_SENTINEL)
        finally:
            if not job_done and self.mode != SHARD_OFF:
                # early exit (terminate / error): detach so our in-flight
                # splits rebind to co-consumers NOW instead of after the
                # liveness deadline; best-effort — the fence is the backstop
                self._detach_quietly(client)
                client = None
            if client is not None:
                client.close()

    def _detach_quietly(self, client):
        """Best-effort DETACH on the early-exit path (reuses the
        maintainer's client when it is still healthy)."""
        try:
            if client is None:
                client = DispatcherClient(self.dispatcher_endpoints, retries=0)
            try:
                client.detach_job(self.job_name, self.consumer_id)
            finally:
                client.close()
        except Exception as e:
            logger.info("servicefeed: detach of %s from job %r not "
                        "delivered (%s)", self.consumer_id, self.job_name, e)

    def _finish_streams(self):
        """Post-completion receiver wind-down — without dropping data.

        At job completion every committed chunk is already in the queue
        (``_commit_split`` publishes before DONE), so receivers are only
        waiting on their ``stream_end`` — or stuck in ``recv`` on a zombie
        stream whose remaining frames are duplicates by construction.
        Give them a short grace to exit cleanly, EOF the stragglers by
        closing their sockets, then join for as long as the consumer is
        alive; the chunk queue is never touched."""
        deadline = time.monotonic() + 2.0
        with self._stream_lock:
            threads = dict(self._streams)
        for worker_id, t in threads.items():
            t.join(timeout=max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                self._close_stream(worker_id)
        for t in threads.values():
            while t.is_alive() and not self._stop.is_set():
                t.join(timeout=0.2)

    def _flush_pending_done(self, client):
        """Retry parked DONE reports (maintainer tick; ``DONE`` is
        idempotent, so at-least-once delivery is safe)."""
        with self._commit_lock:
            pend = list(self._done_pending)
        for key in pend:
            try:
                client.done_split(self.job_name, key[0], key[1],
                                  self.consumer_id)
            except DispatchError as e:
                # a non-transient refusal (job vanished): drop the report
                logger.warning("servicefeed: parked DONE for split %s "
                               "refused (%s)", key, e)
            except (OSError, EOFError, TimeoutError) as e:
                logger.warning("servicefeed: parked DONE for split %s still "
                               "failing (%s)", key, e)
                return
            with self._commit_lock:
                self._done_pending.discard(key)

    def _report_lost_split(self, worker_id, key):
        """Best-effort LOST report: re-pools the mid-flight split now; the
        worker-fence path remains the backstop if this fails."""
        try:
            client = DispatcherClient(self.dispatcher_endpoints)
            try:
                client.lost_split(self.job_name, key[0], key[1], worker_id,
                                  self.consumer_id)
            finally:
                client.close()
        except Exception as e:
            logger.warning("servicefeed: LOST report for split %s on %s "
                           "failed (%s)", key, worker_id, e)

    def _close_stream(self, worker_id):
        with self._stream_lock:
            sock = self._stream_socks.pop(worker_id, None)
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    # -- receive plane -----------------------------------------------------

    def _receive_stream(self, worker_id, meta):
        """One worker stream: dial, hello, then frames until stream_end."""
        tracer = telemetry.get_tracer()
        sock = None
        cur = None       # (epoch, split) being buffered
        pending = []     # buffered chunks of the current split
        retry = False    # lost after a good dial: let the maintainer redial
        try:
            try:
                with tracer.span("dataservice/connect", worker_id=worker_id):
                    sock = self.retry_policy.call(
                        lambda: socket.create_connection(
                            (meta["host"], meta["port"]), timeout=10.0))
            except Exception as e:
                # couldn't reach the worker at all: un-claim the stream slot
                # so the maintainer may retry (bounded by _dial_failures)
                with self._stream_lock:
                    self._dial_failures[worker_id] = (
                        self._dial_failures.get(worker_id, 0) + 1)
                    self._streams.pop(worker_id, None)
                logger.warning("servicefeed: cannot reach worker %s (%s)",
                               worker_id, e)
                return
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._stream_lock:
                self._stream_socks[worker_id] = sock
            hello = {"job": self.job_name, "consumer": self.consumer_id}
            if self.codecs:
                # compression offer: the worker answers by tagging columns
                # with the first codec it supports (raw frames otherwise)
                hello["codecs"] = list(self.codecs)
            _send_json(sock, hello)
            with self._stream_lock:
                # a successful dial+hello proves the worker is healthy:
                # reset its failure budget so a long job survives more
                # than 3 transient stream resets to the same worker
                self._dial_failures.pop(worker_id, None)
            self._last_progress = time.monotonic()
            while not self._stop.is_set():
                kind, payload = _recv_frame(sock)
                # byte-level progress: a single split streaming longer than
                # the watchdog timeout must not trip it while frames flow
                self._last_progress = time.monotonic()
                if kind == _K_JSON:
                    msg = json.loads(payload)
                    mtype = msg.get("type")
                    if mtype == "split_begin":
                        cur = (int(msg["epoch"]), int(msg["split"]))
                        pending = []
                    elif mtype == "split_end":
                        self._tally_split_end(worker_id, msg)
                        self._commit_split(
                            (int(msg["epoch"]), int(msg["split"])), pending,
                            flow=msg.get("flow"))
                        cur, pending = None, []
                    elif mtype == "split_abort":
                        # worker-side reader fault: the stream is healthy
                        # but this split's buffer is incomplete — drop it;
                        # the dispatcher re-pools it or fails the job
                        self.splits_discarded += 1
                        tracer.instant("dataservice/split_abort",
                                       worker_id=worker_id,
                                       split=msg.get("split"))
                        logger.warning(
                            "servicefeed: worker %s aborted split %s (%s)",
                            worker_id, msg.get("split"), msg.get("error"))
                        cur, pending = None, []
                    elif mtype == "stream_end":
                        return
                    continue
                chunk = self._decode(kind, payload)
                if self.mode == SHARD_OFF or cur is None:
                    self._publish(chunk)  # no visitation ledger: commit now
                else:
                    pending.append(chunk)
        except (EOFError, OSError) as e:
            if self._stop.is_set():
                return
            retry = True
            if cur is not None or pending:
                # stream died mid-split: never committed — drop the partial
                # buffer and re-pool it NOW via a LOST report (the worker
                # may be perfectly alive; the fence is only the backstop)
                self.splits_discarded += 1
                tracer.instant("dataservice/split_discard",
                               worker_id=worker_id,
                               split=cur[1] if cur else None)
                if cur is not None and self.mode != SHARD_OFF:
                    self._report_lost_split(worker_id, cur)
            logger.warning("servicefeed: stream to worker %s lost (%s)",
                           worker_id, e)
        except DispatchError as e:
            logger.warning("servicefeed: stream to worker %s aborted (%s)",
                           worker_id, e)
        except Exception as e:
            if not self._stop.is_set():
                self._errors.put(e)
        finally:
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            with self._stream_lock:
                self._stream_socks.pop(worker_id, None)
                if retry and not self._stop.is_set():
                    # un-claim the stream slot so the maintainer redials the
                    # still-live worker (bounded by the same dial budget); a
                    # worker that actually died stops being dialable and
                    # burns out the budget harmlessly
                    self._dial_failures[worker_id] = (
                        self._dial_failures.get(worker_id, 0) + 1)
                    self._streams.pop(worker_id, None)

    def _tally_split_end(self, worker_id, msg):
        """Fold the cache fields a worker rides on ``split_end`` into this
        feed's counters (tallied before the commit so a dedupe-dropped
        duplicate still reports the serve it caused upstream)."""
        verdict = msg.get("cache")
        if verdict == "hit":
            self.cache_hits += 1
        elif verdict == "miss":
            self.cache_misses += 1
        self.cache_bytes += int(msg.get("cache_bytes", 0) or 0)
        self.cache_evictions += int(msg.get("cache_evicted", 0) or 0)
        self.cache_spill_bytes += int(msg.get("cache_spill_bytes", 0) or 0)
        if "cache_resident" in msg:
            self._cache_resident[worker_id] = int(msg["cache_resident"])

    def _decode(self, kind, payload):
        if kind == _K_COLV1:
            # zero-copy: the frombuffer views pin `payload`, which is ours
            info = {}
            chunk = wire.decode_chunk(payload, copy=False, info=info)
            codecs = info.get("codecs")
            # per-link codec attribution: compressed frames count under
            # "colv1+<codec>" so telemetry can split raw from compressed
            fmt = (wire.WIRE_COLV1 + "+" + "+".join(codecs) if codecs
                   else wire.WIRE_COLV1)
            if codecs:
                self.compress_raw_bytes += int(info.get("raw_bytes", 0))
                self.compress_wire_bytes += len(payload)
            n = chunk.count
        elif kind == _K_PICKLE:
            rows = pickle.loads(payload)
            chunk = marker.Chunk(rows)
            fmt = wire.WIRE_PICKLE
            n = len(rows)
        else:
            raise DispatchError("unknown data frame kind {}".format(kind))
        self.wire_formats[fmt] = self.wire_formats.get(fmt, 0) + 1
        self.bytes_received += len(payload)
        return chunk

    def _commit_split(self, key, chunks, flow=None):
        """Exactly-once commit: publish once, report ``DONE`` at-least-once.

        The publish happens exactly once per ``(epoch, split)`` (the
        ``_committed`` dedupe drops a re-streamed copy whole), and only
        THEN is ``DONE`` reported — so the dispatcher can never declare
        the job done while committed chunks are still unpublished.  A
        failed ``DONE`` (transient dispatcher unreachability) parks the
        key in ``_done_pending`` for the maintainer to retry each tick:
        the published data is kept, the ledger catches up when the
        control plane returns, and a duplicate copy streamed meanwhile is
        dropped by the dedupe as usual."""
        with self._commit_lock:
            if key in self._committed:
                self.split_dupes += 1
                self._last_progress = time.monotonic()
                return
            self._committed.add(key)
        for chunk in chunks:
            self._publish(chunk)
        self.splits_committed += 1
        self._last_progress = time.monotonic()
        telemetry.get_tracer().instant(
            "dataservice/split_commit", split=key[1], epoch=key[0],
            consumer=self.consumer_id)
        if flow:
            # continue the dispatcher-started flow in this process and park
            # the id for the infeed/trainer to pick up (pop_flow_id)
            telemetry.get_tracer().flow_step(
                "dataservice/split_flow", flow, leg="split_commit",
                split=key[1], epoch=key[0], consumer=self.consumer_id)
            self._flow_pending.append(int(flow))
        try:
            client = self.retry_policy.call(
                lambda: DispatcherClient(self.dispatcher_endpoints))
            try:
                client.done_split(self.job_name, key[0], key[1],
                                  self.consumer_id)
            finally:
                client.close()
        except (DispatchError, OSError, EOFError, TimeoutError) as e:
            with self._commit_lock:
                self._done_pending.add(key)
            logger.warning("servicefeed: DONE for split %s failed (%s); "
                           "parked for maintainer retry", key, e)

    def _publish(self, item, force=False):
        if item is _SENTINEL:
            if self._sentinel_sent:
                return
            self._sentinel_sent = True
        while True:
            if self._stop.is_set() and not force:
                return
            try:
                self._chunks.put(item, timeout=0.2)
                return
            except _queue.Full:
                if force:
                    # end-of-feed must land even against a full queue a
                    # terminated consumer stopped draining
                    try:
                        self._chunks.get_nowait()
                    except _queue.Empty:
                        pass

    # -- consumer surface (DataFeed duck type) -----------------------------

    def _get_interruptible(self):
        if not self._errors.empty():
            raise self._errors.get()
        # chaos hook: ``saturate_consumer_secs`` slow-drains this pop so
        # the prefetch queue pins at capacity (NULL injector: one no-op)
        self._fault.on_consume()
        t0 = time.monotonic()
        try:
            while not self._interrupt.is_set():
                try:
                    item = self._chunks.get(block=True, timeout=0.5)
                except _queue.Empty:
                    if not self._errors.empty():
                        raise self._errors.get()
                    continue
                if item is _SENTINEL:
                    self._done = True
                    if not self._errors.empty():
                        raise self._errors.get()
                return item
            return _INTERRUPTED
        finally:
            self.stall_secs += time.monotonic() - t0

    def _buflen(self):
        buf = self._buffer
        return buf.count if isinstance(buf, marker.ColChunk) else len(buf)

    def _bufrow(self, i):
        buf = self._buffer
        return buf.row(i) if isinstance(buf, marker.ColChunk) else buf[i]

    def _next_chunk(self):
        """Refill the row buffer; False at end-of-feed/interrupt."""
        while True:
            if self._done:
                return False
            item = self._get_interruptible()
            if item is _INTERRUPTED or item is _SENTINEL:
                return False
            self._buffer = (item.items if isinstance(item, marker.Chunk)
                            else item)
            self._buffer_idx = 0
            if self._buflen():
                return True

    def next_batch(self, batch_size):
        """Up to ``batch_size`` rows; a list of items, or a dict of
        per-tensor lists when ``input_mapping`` was given (the
        ``DataFeed.next_batch`` contract)."""
        self._ensure_started()
        tensors = ([] if self.input_tensors is None
                   else {tensor: [] for tensor in self.input_tensors})
        count = 0
        while count < batch_size:
            if self._buffer_idx >= self._buflen():
                if not self._next_chunk():
                    break
            item = self._bufrow(self._buffer_idx)
            self._buffer_idx += 1
            if self.input_tensors is None:
                tensors.append(item)
            else:
                for i, tensor in enumerate(self.input_tensors):
                    tensors[tensor].append(item[i])
            count += 1
        self.items_consumed += count
        self._fault.on_items(count)
        return tensors

    def next_batch_arrays(self, batch_size, dtypes=None):
        """Columnar ``(arrays, count)`` — the ``DataFeed.next_batch_arrays``
        contract: per-tensor dict with ``input_mapping``, tuple of field
        arrays for tuple rows, single array for single-value rows, dict of
        per-key columns for dict rows (the ``FileFeed`` surface)."""
        from tensorflowonspark_tpu import datafeed

        self._ensure_started()
        parts = []       # per-part tuple of per-field array slices
        dict_rows = []   # dict-row accumulation (pickle-fallback path)
        tuple_rows = None
        count = 0
        while count < batch_size:
            buflen = self._buflen()
            if self._buffer_idx >= buflen:
                if not self._next_chunk():
                    break
                buflen = self._buflen()
            take = min(batch_size - count, buflen - self._buffer_idx)
            i0 = self._buffer_idx
            buf = self._buffer
            if isinstance(buf, marker.ColChunk):
                fields, tr = tuple(c[i0:i0 + take]
                                   for c in buf.columns), buf.tuple_rows
            elif buf and isinstance(buf[0], dict):
                if parts:
                    raise ValueError("mixed dict and non-dict rows across "
                                     "feed chunks")
                dict_rows.extend(buf[i0:i0 + take])
                self._buffer_idx += take
                count += take
                continue
            else:
                fields, tr = datafeed._rows_to_fields(buf[i0:i0 + take])
            if dict_rows:
                raise ValueError("mixed dict and non-dict rows across feed "
                                 "chunks")
            if tuple_rows is None:
                tuple_rows = tr
            elif tuple_rows != tr or (parts
                                      and len(parts[-1]) != len(fields)):
                raise ValueError(
                    "inconsistent row structure across feed chunks "
                    "(tuple_rows {} vs {})".format(tuple_rows, tr))
            parts.append(fields)
            self._buffer_idx += take
            count += take
        self.items_consumed += count
        self._fault.on_items(count)
        if dict_rows:
            from tensorflowonspark_tpu.data import FileFeed

            return FileFeed._columnar(dict_rows, dtypes), count
        if not count:
            return (np.empty((0,)) if self.input_tensors is None
                    else {t: np.empty((0,)) for t in self.input_tensors}), 0
        return datafeed.assemble_columns(parts, tuple_rows, dtypes,
                                         self.input_tensors), count

    def should_stop(self):
        """True once end-of-feed was observed and the buffer is drained."""
        return self._done and self._buffer_idx >= self._buflen()

    def interrupt(self):
        """Unblock a concurrent ``next_batch*`` (ShardedFeed handoff)."""
        self._interrupt.set()

    def terminate(self):
        """Stop receiving, close streams, drop buffered data (early stop /
        preemption drain).  Idempotent."""
        self._interrupt.set()
        self._stop.set()
        with self._stream_lock:
            workers = list(self._stream_socks)
        for worker_id in workers:
            self._close_stream(worker_id)
        if self._maintainer is not None:
            self._maintainer.join(timeout=2.0)
        while True:
            try:
                self._chunks.get_nowait()
            except _queue.Empty:
                break
        self._buffer, self._buffer_idx = [], 0
        self._done = True

    def pop_flow_id(self):
        """Oldest undrained trace-flow id of a committed split (or None).

        Drained by the downstream :class:`~...parallel.infeed.ShardedFeed` /
        :class:`~...train.Trainer` so the dispatcher-started flow event
        chain continues through device infeed and dispatch.  Best-effort:
        ids of splits nobody drained age out of the bounded deque."""
        try:
            return self._flow_pending.popleft()
        except IndexError:
            return None

    def counters_snapshot(self):
        """Flat telemetry counters for heartbeat payloads (the
        ``dataservice_*`` vocabulary merged into
        ``TPUCluster.metrics_snapshot()``)."""
        snap = {"dataservice_items": self.items_consumed,
                "dataservice_stall_secs": round(self.stall_secs, 6),
                "dataservice_splits": self.splits_committed,
                "dataservice_split_dupes": self.split_dupes,
                "dataservice_splits_discarded": self.splits_discarded,
                "dataservice_bytes": self.bytes_received}
        try:
            # Instantaneous prefetch-queue fill percentage, sampled per
            # beat: pinned at 100 the producer outruns the consumer (the
            # watchtower's saturation rule); pinned at 0 with stalls the
            # feed workers are the bottleneck.
            cap = self._chunks.maxsize
            if cap:
                snap["dataservice_queue_sat_pct_max"] = round(
                    100.0 * self._chunks.qsize() / cap, 2)
                # gauge: the CURRENT bound, so the driver can confirm a
                # live autopilot retune landed
                snap["dataservice_queue_bound_max"] = cap
        except Exception:
            pass
        for fmt, n in list(self.wire_formats.items()):
            snap["wire_{}".format(fmt)] = n
        # worker cache telemetry (relayed on split_end): always present so
        # dashboards see zeros, not gaps, when the cache is disabled
        snap["dataservice_cache_hit"] = self.cache_hits
        snap["dataservice_cache_miss"] = self.cache_misses
        snap["dataservice_cache_bytes"] = self.cache_bytes
        snap["dataservice_cache_evictions"] = self.cache_evictions
        snap["dataservice_cache_spill_bytes"] = self.cache_spill_bytes
        if self._cache_resident:
            snap["dataservice_cache_resident_max"] = max(
                self._cache_resident.values())
        # job-level affinity counters (polled off STATUS by the maintainer):
        # hits / total DYNAMIC hand-outs — the scheduler's A/B metric
        aff = self._affinity
        if aff.get("total"):
            snap["dataservice_affinity_hits"] = aff.get("hits", 0)
            snap["dataservice_affinity_total"] = aff["total"]
            snap["dataservice_affinity_hit_pct_max"] = round(
                100.0 * aff.get("hits", 0) / aff["total"], 2)
        if self.compress_wire_bytes:
            from . import metrics as _metrics
            snap["wire_compress_saved_bytes"] = (
                self.compress_raw_bytes - self.compress_wire_bytes)
            snap["wire_compress_ratio_max"] = round(_metrics.compression_ratio(
                self.compress_raw_bytes, self.compress_wire_bytes), 4)
        return snap

    def apply_knob(self, name, value):
        """Live-knob hook (autopilot KNOB pushes; see docs/AUTOPILOT.md).

        - ``dataservice_queue_bound``: rebounds the RUNNING chunk queue in
          place (under its mutex, waking blocked putters), so receiver
          threads can buffer deeper on the very next frame.
        - ``wire_codec``: re-resolves the codec offer (``"off"`` offers
          nothing, ``"auto"`` re-resolves the host default, a codec name
          offers just it); negotiated per stream hello, so it affects
          future dials — late-joining workers, re-dials, the next feed.
        - ``dataservice_cache_budget``: relayed to the dispatcher as a
          KNOB message (on a short-lived thread — this hook runs on the
          node's heartbeat thread) to ride the worker heartbeat replies.

        Returns True when the knob was claimed."""
        if name == "dataservice_queue_bound":
            bound = max(int(value), 2)
            q = self._chunks
            with q.mutex:
                q.maxsize = bound
                q.not_full.notify_all()
            return True
        if name == "wire_codec":
            if value in (None, "auto"):
                self.codecs = _resolve_codecs(None)
            elif str(value).lower() in ("off", "0", "none", "pickle"):
                self.codecs = []
            elif wire.codec_supported(str(value)):
                self.codecs = [str(value)]
            else:
                logger.warning("wire_codec knob: %r unsupported on this "
                               "host; ignored", value)
                return False
            return True
        if name == "dataservice_cache_budget":
            budget = int(value)

            def _relay():
                try:
                    client = DispatcherClient(self.dispatcher_endpoints,
                                              retries=0)
                    try:
                        client.push_knobs(
                            {"dataservice_cache_budget": budget})
                    finally:
                        client.close()
                except Exception as e:
                    logger.warning("cache-budget knob relay failed (%s)", e)

            threading.Thread(target=_relay, name="tfos-knob-relay",
                             daemon=True).start()
            return True
        return False
