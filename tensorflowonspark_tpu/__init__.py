"""tensorflowonspark_tpu — a TPU-native cluster-bootstrap and data-feeding framework.

A brand-new framework with the capabilities of TensorFlowOnSpark
(reference: ``tensorflowonspark/__init__.py``, ``README.md``): it turns a generic
task-scheduling cluster (Apache Spark when available, or the built-in multi-process
local backend) into a distributed JAX/TPU cluster.  Where the reference bootstraps a
``TF_CONFIG`` worker/PS gRPC mesh with NCCL allreduce on GPUs
(reference ``TFSparkNode.py:278-286``), this framework bootstraps a
``jax.distributed`` coordinator plus a ``jax.sharding.Mesh`` over TPU-pod hosts,
with collectives running on ICI/DCN and data entering via per-host batched infeed.

Layer map (mirrors reference SURVEY layers L2-L5, re-designed TPU-first):

- :mod:`~tensorflowonspark_tpu.cluster`     — driver-side lifecycle API
  (``run/train/inference/shutdown``; reference ``TFCluster.py``)
- :mod:`~tensorflowonspark_tpu.node`        — per-executor node runtime
  (reference ``TFSparkNode.py``)
- :mod:`~tensorflowonspark_tpu.reservation` — rendezvous server/client
  (JSON over TCP; reference ``reservation.py`` used pickled messages)
- :mod:`~tensorflowonspark_tpu.manager`     — per-executor IPC broker
  (reference ``TFManager.py``)
- :mod:`~tensorflowonspark_tpu.datafeed`    — user-side data feed, batched for
  TPU infeed (reference ``TFNode.py``)
- :mod:`~tensorflowonspark_tpu.backend`     — cluster execution backends
  (Spark when pyspark is installed, built-in LocalBackend otherwise)
- :mod:`~tensorflowonspark_tpu.pipeline`    — ML Estimator/Model pipeline
  (reference ``pipeline.py``)
- :mod:`~tensorflowonspark_tpu.dfutil`      — TFRecord <-> rows converters
  (reference ``dfutil.py``; codec is first-party C++/Python, no Hadoop jar)
- :mod:`~tensorflowonspark_tpu.parallel`    — device meshes, collectives,
  sequence parallelism (ring attention) — the TPU-native data plane that replaces
  the reference's delegated gRPC/NCCL layer
- :mod:`~tensorflowonspark_tpu.models`      — flax model zoo for the example
  workloads (MNIST CNN, ResNet, U-Net, Transformer LM)
"""

import logging
import os

# Match the reference's package-wide logging setup (reference __init__.py:1-5):
# INFO level with thread/process ids so interleaved executor logs are
# attributable.  basicConfig is a no-op if the application already configured
# the root logger; set TFOS_TPU_NO_LOG_CONFIG=1 to suppress it entirely.
if not os.environ.get("TFOS_TPU_NO_LOG_CONFIG"):
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s (%(threadName)s-%(process)d) %(message)s",
    )

__version__ = "0.1.0"
