"""Checkpoint / export conventions (reference SURVEY §5.4).

The reference delegated checkpointing to TF inside ``main_fun`` (Keras
``ModelCheckpoint``; estimator ``save_checkpoints_steps``) and contributed the
*conventions*: ``model_dir``/``export_dir`` args, chief-only export
(reference ``mnist_spark.py:68-72``), shared-storage path normalization, and
a shutdown grace period so the chief finishes exporting
(``TFCluster.py:123``, ``TFSparkNode.py:542-545``).

This module implements those conventions over orbax:

- :class:`CheckpointManager` — periodic, retained, atomic checkpoints of any
  pytree (TrainState), chief-only by default, with restore-latest for
  mid-training recovery (the reference's recovery story was "Spark retries
  the job and TF restores from the last checkpoint", SURVEY §5.3).
- :func:`export_model` / :func:`load_model` — the serving export consumed by
  the pipeline's model-transform path (reference SavedModel; here an orbax
  params checkpoint + a JSON descriptor naming the apply function).
"""

import json
import logging
import os
import queue as _queue
import threading

logger = logging.getLogger(__name__)

_DESCRIPTOR = "export.json"
_PARAMS_DIR = "params"

#: async ``maybe_save`` toggle (default ON): "0"/"off" forces the legacy
#: synchronous save, where maybe_save blocks the dispatch loop for the full
#: serialization+write.  See :class:`CheckpointManager`.
ASYNC_CKPT_ENV = "TFOS_ASYNC_CKPT"

#: how long :meth:`CheckpointManager.close` waits for the async worker
_CLOSE_JOIN_SECS = 120.0


def _fs_path(path):
    """Resolve a (possibly ``file://``-prefixed) path for local-fs IO.

    ``ctx.absolute_path`` hands out ``file://`` URIs (reference ``hdfs_path``
    convention); strip the scheme so ``os`` / ``open`` treat it as the local
    path it names.  Other schemes (``gs://`` etc.) pass through for
    orbax-compatible stores.
    """
    from tensorflowonspark_tpu import fsio

    path = fsio.strip_file_scheme(path)
    return path if fsio.is_remote(path) else os.path.abspath(path)


def aot_root(directory):
    """The AOT executable store beside a checkpoint root.

    Warm rejoin and restore share one directory tree: a replacement node
    that can see the checkpoints can also see the serialized step
    executables (:mod:`~tensorflowonspark_tpu.compilecache`), so
    ``fit_supervised`` restores state AND dispatches without retracing
    from the same mount.  The subdirectory name is outside the
    ``ckpt-<step>`` namespace, so checkpoint retention/quarantine never
    touches it.
    """
    return os.path.join(_fs_path(directory), "aot_executables")


def _nonfinite_leaves(state):
    """Key paths of floating-point leaves holding any NaN/Inf — the
    poison-step marker :meth:`CheckpointManager.restore_latest_valid` uses
    to quarantine checkpoints saved AFTER a nonfinite update landed.  One
    device sync per float leaf; recovery-path only."""
    import jax
    import jax.numpy as jnp

    bad = []
    for path, leaf in jax.tree_util.tree_leaves_with_path(state):
        dtype = getattr(leaf, "dtype", None)
        if dtype is None or not jnp.issubdtype(dtype, jnp.floating):
            continue
        if not bool(jnp.all(jnp.isfinite(leaf))):
            bad.append(jax.tree_util.keystr(path) or "<root>")
    return bad


class CheckpointManager(object):
    """Chief-only periodic checkpointing of a train-state pytree.

    Args:
      directory: checkpoint root (shared storage in multi-host runs).
      save_interval_steps: save every N steps (0 = only explicit saves).
      max_to_keep: retained checkpoints.
      is_chief: informational; orbax itself writes from the primary host
        only.  Every host MUST still call :meth:`maybe_save` — the save is a
        cross-process collective (all hosts contribute their array shards
        and enter a sync barrier), so gating the *call* on chiefness would
        deadlock multi-host runs.  The reference's chief-only pattern
        applies to the single-file export path, not here.
      async_save: ``True`` (the default; ``None`` reads ``TFOS_ASYNC_CKPT``)
        makes :meth:`maybe_save` return as soon as the state is snapshotted
        to fresh device buffers and handed to a background worker thread —
        the orbax serialization + write overlap the next dispatches instead
        of stalling the step loop.  The snapshot is **donation-safe**: a
        jitted device-side copy of every ``jax.Array`` leaf, so the very
        next train step may donate the live state without garbling the save
        in flight.  At most one save is queued and one in flight (a
        ``Queue(maxsize=1)`` blocking put is the backpressure: a third save
        request waits, bounding extra state copies to two).  All read paths
        (:meth:`restore_latest`, :meth:`restore_latest_valid`,
        :meth:`latest_step`, :meth:`wait_until_finished`, :meth:`close`)
        drain pending saves first, and a worker failure surfaces on the
        next :meth:`maybe_save` or :meth:`wait_until_finished` — a save is
        never silently lost.  ``False`` restores the legacy synchronous
        behavior.
    """

    def __init__(self, directory, save_interval_steps=100, max_to_keep=3,
                 is_chief=True, async_save=None):
        import orbax.checkpoint as ocp

        self.directory = _fs_path(directory)
        self.is_chief = is_chief
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                save_interval_steps=save_interval_steps or 1,
                max_to_keep=max_to_keep,
                create=True,
            ),
        )
        self.save_interval_steps = save_interval_steps
        if async_save is None:
            async_save = os.environ.get(ASYNC_CKPT_ENV, "1").lower() not in (
                "0", "off", "false", "")
        self.async_save = bool(async_save)
        self._save_queue = _queue.Queue(maxsize=1)
        self._save_thread = None      # started lazily on first async save
        self._save_error = None       # worker exception, re-raised at a sync
        self._last_requested = None   # newest step handed to the worker
        self._copy_fn = None          # cached jitted device-side leaf copy
        # Resolved once: the corrupt_checkpoint fault fires ONCE per process,
        # and a fresh from_env() per save would re-arm it every time.
        from tensorflowonspark_tpu import fault

        self._injector = fault.from_env()

    def _latest_effective(self):
        """Newest step saved OR handed to the async worker: the save gates
        must be computed against requested steps, not just landed ones —
        orbax's ``latest_step`` lags while a save is in flight, and gating
        on it alone would enqueue the same boundary twice."""
        latest = self._mgr.latest_step()
        if self._last_requested is not None and (
                latest is None or self._last_requested > latest):
            return self._last_requested
        return latest

    def maybe_save(self, step, state, force=False):
        """Save if an interval boundary was CROSSED since the last save;
        returns True if a save landed (sync) or was accepted (async).

        Boundary-crossing (not ``step % interval == 0``): callers that see
        steps at a stride — ``fit_feed(steps_per_call=K)`` reports once per
        K-step dispatch, possibly offset by a restored step — would
        otherwise save never (misaligned residues) or at lcm(K, interval).

        Must be called by ALL hosts each step (collective; see class doc) —
        the check below is deterministic so hosts agree.  Async mode keeps
        that determinism: the gate decides at enqueue time from locally-
        tracked request state, the snapshot is taken synchronously (device-
        side copy — cheap), and only the orbax serialization/write moves to
        the worker, in strict request order on every host."""
        self._raise_pending_error()
        if not force:
            if not self.save_interval_steps:
                return False  # interval 0: explicit (force=True) saves only
            last = self._latest_effective() or 0
            if (step // self.save_interval_steps
                    <= last // self.save_interval_steps):
                return False
        if step == self._latest_effective():
            return False  # already saved (e.g. final force after interval hit)
        import orbax.checkpoint as ocp

        from tensorflowonspark_tpu import telemetry

        if self.async_save:
            snapshot = self._snapshot_for_save(state)
            self._ensure_worker()
            telemetry.get_tracer().instant("checkpoint/save_requested",
                                           step=step, force=force)
            # Blocking put is the backpressure: with one save in flight and
            # one queued, a third request waits here instead of stacking
            # unbounded state snapshots.
            self._save_queue.put((step, snapshot, force))
            self._last_requested = step
            return True

        with telemetry.get_tracer().span("checkpoint/save", step=step,
                                         force=force):
            saved = self._mgr.save(step, args=ocp.args.StandardSave(
                _globalize(state)), force=force)
        if saved:
            logger.info("checkpointed step %d to %s", step, self.directory)
            self._maybe_inject_corruption()
        return saved

    # -- async save machinery ---------------------------------------------

    def _raise_pending_error(self):
        if self._save_error is not None:
            err, self._save_error = self._save_error, None
            # Re-derive the request watermark from what actually landed, so
            # a retry after the failure can save the same step again.
            self._last_requested = self._mgr.latest_step()
            raise err

    def _snapshot_for_save(self, state):
        """Donation-safe snapshot: fresh device-side copies of every
        ``jax.Array`` leaf (jitted — legal on multi-host global arrays,
        where eager copies are rejected; PJRT orders the copy before any
        later donation of the originals), ``np.copy`` for host arrays.
        Cached single compilation — the state structure is fixed."""
        import jax
        import numpy as np

        leaves, treedef = jax.tree_util.tree_flatten(state)
        device_ix = [i for i, l in enumerate(leaves)
                     if isinstance(l, jax.Array)]
        if device_ix:
            if self._copy_fn is None:
                import jax.numpy as jnp

                self._copy_fn = jax.jit(
                    lambda xs: [jnp.copy(x) for x in xs])
            copies = self._copy_fn([leaves[i] for i in device_ix])
            for i, c in zip(device_ix, copies):
                leaves[i] = c
        for i, l in enumerate(leaves):
            if isinstance(l, np.ndarray):
                leaves[i] = np.copy(l)
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def _ensure_worker(self):
        if self._save_thread is not None and self._save_thread.is_alive():
            return
        t = threading.Thread(target=self._save_worker, name="ckpt-async-save",
                             daemon=True)
        self._save_thread = t
        t.start()

    def _save_worker(self):
        import orbax.checkpoint as ocp

        from tensorflowonspark_tpu import telemetry

        while True:
            item = self._save_queue.get()
            try:
                if item is None:
                    return
                step, state, force = item
                # force=True always: the maybe_save gate IS the policy and
                # already passed at enqueue time; orbax's own interval check
                # (which disagrees with boundary-crossing at step strides)
                # must not silently drop an accepted save.
                with telemetry.get_tracer().span(
                        "checkpoint/save", step=step, force=force,
                        asynchronous=True):
                    self._mgr.save(step, args=ocp.args.StandardSave(
                        _globalize(state)), force=True)
                logger.info("checkpointed step %d to %s (async)", step,
                            self.directory)
                self._maybe_inject_corruption()
            except BaseException as e:  # surfaced at the next sync point
                logger.exception("async checkpoint save of step %s failed",
                                 item[0] if item else "?")
                self._save_error = e
            finally:
                self._save_queue.task_done()

    def _maybe_inject_corruption(self):
        if self._injector.enabled:
            # chaos only: the injector garbles finalized step dirs, so
            # flush the async save before handing it the directory
            self._mgr.wait_until_finished()
            self._injector.corrupt_checkpoint(self.directory)

    def _drain_pending(self):
        """Block until every queued async save has been handed to orbax
        (the orbax-internal async commit is flushed separately by
        ``_mgr.wait_until_finished``)."""
        if self._save_thread is not None and self._save_thread.is_alive():
            self._save_queue.join()

    def restore_latest(self, abstract_state):
        """Restore the newest checkpoint into the structure of
        ``abstract_state``; returns (state, step) or (None, None).

        Re-reads the step list from storage first: orbax caches it at
        manager creation, and the callers of this method (recovery after
        restart, a polling evaluator node) are exactly the ones racing
        another process's writes."""
        self._drain_pending()
        self._mgr.wait_until_finished()
        self._mgr.reload()
        step = self._mgr.latest_step()
        if step is None:
            return None, None
        import orbax.checkpoint as ocp

        from tensorflowonspark_tpu import telemetry

        with telemetry.get_tracer().span("checkpoint/restore", step=step):
            state = self._mgr.restore(
                step, args=ocp.args.StandardRestore(abstract_state))
        logger.info("restored checkpoint step %d from %s", step, self.directory)
        return state, step

    def restore_latest_valid(self, abstract_state):
        """Like :meth:`restore_latest`, but VALIDATE before trusting: a
        checkpoint can be partial (the writer was preempted mid-finalize) or
        corrupt (bit rot, injected faults), and recovery crashing on it
        defeats the point of retaining ``max_to_keep`` steps.

        Per candidate (newest first): the step dir must exist under its
        final (committed) name with content, the restore itself must
        succeed into ``abstract_state`` — the restore is the authoritative
        structure/integrity check, there is no cheaper proxy orbax exposes —
        and every floating-point leaf must be FINITE (a checkpoint saved
        after a poison step carries NaN/Inf params; restoring it would
        resume training on poisoned state, which is exactly what the
        remediator's rollback exists to undo).
        An invalid step is QUARANTINED by renaming its dir to
        ``<step>.corrupt`` (orbax no longer lists it; operators can inspect
        it), then the previous retained step is tried.  Returns
        ``(state, step)`` from the newest valid step, or ``(None, None)``
        when no valid checkpoint remains (train from scratch)."""
        import orbax.checkpoint as ocp

        from tensorflowonspark_tpu import telemetry

        self._drain_pending()
        self._mgr.wait_until_finished()
        tracer = telemetry.get_tracer()
        tried = set()
        while True:
            self._mgr.reload()
            step = self._mgr.latest_step()
            if step is None:
                return None, None
            if step in tried:
                # quarantine did not remove it from the listing; give up
                # rather than loop forever
                logger.error("checkpoint step %d remains listed after "
                             "quarantine; recovering from scratch", step)
                return None, None
            tried.add(step)
            step_dir = os.path.join(self.directory, str(step))
            try:
                with tracer.span("checkpoint/restore", step=step,
                                 validated=True):
                    if not os.path.isdir(step_dir) or not os.listdir(step_dir):
                        raise ValueError(
                            "step dir {} missing or empty (uncommitted "
                            "save)".format(step_dir))
                    state = self._mgr.restore(
                        step, args=ocp.args.StandardRestore(abstract_state))
                    poisoned = _nonfinite_leaves(state)
                    if poisoned:
                        raise ValueError(
                            "nonfinite values in restored state: {}".format(
                                ", ".join(poisoned[:4])))
            except Exception:
                logger.warning(
                    "checkpoint step %d failed validation; quarantining and "
                    "falling back to the previous retained step", step,
                    exc_info=True)
                tracer.instant("checkpoint/quarantine", step=step)
                self._quarantine(step_dir)
                continue
            logger.info("restored validated checkpoint step %d from %s",
                        step, self.directory)
            return state, step

    @staticmethod
    def _quarantine(step_dir):
        """Rename a bad step dir to ``<step>.corrupt`` (suffixed ``.N`` if
        taken) so orbax stops listing it; tolerates a dir that is already
        gone."""
        if not os.path.isdir(step_dir):
            return
        target = step_dir + ".corrupt"
        n = 0
        while os.path.exists(target):
            n += 1
            target = "{}.corrupt.{}".format(step_dir, n)
        try:
            os.rename(step_dir, target)
            logger.warning("quarantined bad checkpoint: %s -> %s",
                           step_dir, target)
        except OSError:
            logger.exception("could not quarantine %s", step_dir)

    def latest_step(self, reload=True):
        """Newest saved step, or None.  ``reload=True`` re-reads the step
        list from storage (orbax caches it), so polling evaluators can
        probe for new checkpoints cheaply without a full restore.  Pending
        async saves are flushed first, so "latest" includes every accepted
        :meth:`maybe_save`."""
        self._drain_pending()
        self._mgr.wait_until_finished()
        if reload:
            self._mgr.reload()
        return self._mgr.latest_step()

    def wait_until_finished(self):
        """Barrier: every accepted save is durably on storage when this
        returns, and a failed async save raises here instead of vanishing.
        Called on all exit paths (end-of-fit, preemption drain, emergency
        save) — see :func:`~tensorflowonspark_tpu.train.fit_supervised`."""
        self._drain_pending()
        self._mgr.wait_until_finished()
        self._raise_pending_error()

    def close(self):
        """Flush pending saves, stop the async worker, close orbax.  Never
        raises for a failed in-flight save (close runs on unwind paths);
        the failure is logged by the worker."""
        if self._save_thread is not None and self._save_thread.is_alive():
            try:
                self._save_queue.join()
            except Exception:  # pragma: no cover - defensive
                pass
            self._save_queue.put(None)  # shutdown sentinel
            self._save_thread.join(timeout=_CLOSE_JOIN_SECS)
            if self._save_thread.is_alive():  # pragma: no cover - wedged fs
                logger.error("async checkpoint worker did not exit within "
                             "%.0fs; abandoning it", _CLOSE_JOIN_SECS)
        self._mgr.close()


def abstract_state(state):
    """Abstract (shape/dtype/sharding) view of a live state pytree — the
    template :meth:`CheckpointManager.restore_latest` restores into, so the
    restored arrays land with the SAME sharding the running state uses
    (restore-then-reshard would double peak memory)."""
    import jax
    import numpy as np

    def one(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        arr = np.asarray(x)
        return jax.ShapeDtypeStruct(arr.shape, arr.dtype)

    return jax.tree_util.tree_map(one, state)


def _globalize(tree):
    """Make every leaf serializable in multi-host worlds.

    Orbax refuses host-local ``jax.Array`` leaves when
    ``process_count() > 1`` (e.g. a bare ``jnp.asarray(step)`` counter that
    never went through a mesh sharding).  Such leaves are per-host values
    that are identical across hosts by construction (step counters, scalars
    computed from the replicated state), so re-wrap them as globally
    replicated arrays over all devices.  Mesh-sharded/global leaves pass
    through untouched.  No-op in single-process worlds.
    """
    import jax

    if jax.process_count() <= 1:
        return tree
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    mesh = Mesh(np.asarray(jax.devices()), ("_ckpt",))
    replicated = NamedSharding(mesh, PartitionSpec())

    def one(x):
        if isinstance(x, jax.Array) and x.is_fully_addressable:
            host = np.asarray(jax.device_get(x))
            return jax.make_array_from_callback(
                host.shape, replicated, lambda idx: host[idx])
        return x

    return jax.tree_util.tree_map(one, tree)


def should_export(ctx):
    """Who calls :func:`export_model` under the chief-only convention.

    - Single-process worlds (each executor its own jax runtime, e.g.
      InputMode.SPARK without ``initialize_distributed``): chief only —
      others writing the same dir would race.
    - Multi-process worlds (``ctx.initialize_distributed()`` ran): EVERY
      process — the orbax save is a cross-process collective (all hosts
      contribute shards + sync barrier); gating on chiefness would crash
      or deadlock the collective.  Only the primary actually writes.
    """
    import jax

    return jax.process_count() > 1 or ctx.is_chief()


_STABLEHLO_FILE = "apply.stablehlo"


_EMBEDDED_MLIR_FILE = "apply_embedded.mlir"
_COMPILE_OPTIONS_FILE = "compile_options.pb"


def export_model(export_dir, params, model_name, model_config=None,
                 input_signature=None, model=None,
                 serialize_platforms=("cpu", "tpu"),
                 embed_batch_size=None, embed_platform="tpu"):
    """Export params + model descriptor for serving.

    Call according to :func:`should_export` (chief-only convention,
    reference ``mnist_spark.py:68-72``; collective in multi-process worlds).
    The pipeline's model-transform path loads this on executors — the
    portability role SavedModel played for the reference
    (``pipeline.py:474-481``).

    When ``model`` (the flax module) and ``input_signature`` are given, the
    serving fn is ALSO serialized to portable StableHLO (``jax.export``,
    batch-polymorphic, lowered for ``serialize_platforms``): serving hosts
    then need jax alone — no flax, no model registry, no user code (the
    reference's user-code-free SavedModel/JNI path,
    ``TFModel.scala:245-292``).  Registry-based serving remains the
    fallback whenever the artifact is absent or platform-mismatched.

    ``embed_batch_size`` additionally writes a **params-embedded**,
    fixed-batch StableHLO module (+ serialized compile options) for the
    native C++ PJRT runner (``native/pjrt_runner.cc``) — serving with no
    Python at all; ``embed_platform`` picks its single lowering target.
    """
    import jax
    import orbax.checkpoint as ocp

    # Cross-process-sharded params (e.g. Trainer(param_sharding="fsdp") on
    # a multi-host mesh) are not fully addressable: device_get below would
    # raise after a full training run.  Re-replicate through a jit identity
    # (SPMD all-gather) first; fully-addressable trees pass through as-is.
    leaves = [l for l in jax.tree_util.tree_leaves(params)
              if isinstance(l, jax.Array)]
    if any(not l.is_fully_addressable for l in leaves):
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = next(l.sharding.mesh for l in leaves
                    if not l.is_fully_addressable)
        params = jax.jit(
            lambda p: p,
            out_shardings=NamedSharding(mesh, PartitionSpec()))(params)

    export_dir = _fs_path(export_dir)
    os.makedirs(export_dir, exist_ok=True)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(export_dir, _PARAMS_DIR), _globalize(params),
               force=True)
    ckptr.wait_until_finished()
    ckptr.close()
    descriptor = {
        "model_name": model_name,
        "model_config": model_config or {},
        "input_signature": input_signature or {},
    }
    if model is not None and input_signature and jax.process_index() == 0:
        from tensorflowonspark_tpu import serving

        try:
            blob, platforms = serving.serialize_apply(
                model, jax.device_get(params), input_signature,
                platforms=serialize_platforms)
            with open(os.path.join(export_dir, _STABLEHLO_FILE), "wb") as f:
                f.write(blob)
            descriptor["stablehlo"] = {"file": _STABLEHLO_FILE,
                                       "platforms": list(platforms)}
        except Exception:
            # The orbax+registry path still serves; don't fail the export.
            logger.warning("StableHLO serialization failed; export remains "
                           "registry-served", exc_info=True)
        if embed_batch_size:
            try:
                mlir, options, meta = serving.serialize_embedded(
                    model, jax.device_get(params), input_signature,
                    batch_size=embed_batch_size, platform=embed_platform)
                with open(os.path.join(export_dir, _EMBEDDED_MLIR_FILE),
                          "wb") as f:
                    f.write(mlir)
                with open(os.path.join(export_dir, _COMPILE_OPTIONS_FILE),
                          "wb") as f:
                    f.write(options)
                meta["file"] = _EMBEDDED_MLIR_FILE
                meta["options_file"] = _COMPILE_OPTIONS_FILE
                descriptor["embedded_mlir"] = meta
            except Exception:
                logger.warning("embedded-MLIR serialization failed; native "
                               "runner artifact omitted", exc_info=True)
    if jax.process_index() == 0:
        with open(os.path.join(export_dir, _DESCRIPTOR), "w") as f:
            json.dump(descriptor, f)
    logger.info("exported %s to %s", model_name, export_dir)


def load_model(export_dir, validate=False):
    """Load an export: returns ``(params, descriptor_dict)``.

    ``validate=True`` additionally runs the nonfinite-leaf scan
    :func:`restore_latest_valid` applies to training checkpoints and
    raises ``ValueError`` on a poisoned export — the fleet's live-swap
    path refuses to flip a replica onto NaN/Inf weights.
    """
    import orbax.checkpoint as ocp

    export_dir = _fs_path(export_dir)
    with open(os.path.join(export_dir, _DESCRIPTOR)) as f:
        descriptor = json.load(f)
    ckptr = ocp.StandardCheckpointer()
    params = ckptr.restore(os.path.join(export_dir, _PARAMS_DIR))
    ckptr.close()
    if validate:
        bad = _nonfinite_leaves(params)
        if bad:
            raise ValueError(
                "export {} has nonfinite params at {}".format(
                    export_dir, bad[:4]))
    return params, descriptor
