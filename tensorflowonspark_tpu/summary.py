"""TensorBoard scalar summaries with no TensorFlow dependency.

The reference's training curves come from Keras/estimator summary writers
inside the user fn (reference ``examples/mnist/keras/mnist_spark.py``
TensorBoard callback); the framework launches TensorBoard on the chief
(``node.py``) but had nothing writing scalar events.  This module closes
that: :class:`SummaryWriter` emits standard ``events.out.tfevents.*`` files
— TFRecord-framed ``tensorflow.Event`` protos, hand-encoded on the same
wire helpers as :mod:`~tensorflowonspark_tpu.example_proto` and framed by
the native TFRecord codec — readable by stock TensorBoard.

Wire schema (tensorflow/core/util/event.proto, public format):

- ``Event``: ``double wall_time = 1`` (64-bit), ``int64 step = 2``
  (varint), ``string file_version = 3``, ``Summary summary = 5``.
- ``Summary``: ``repeated Value value = 1``; ``Value``: ``string tag = 1``,
  ``float simple_value = 2`` (32-bit).

Usage (chief-only, like every reference example; local paths only —
``file://`` is stripped, remote schemes are rejected)::

    with summary.SummaryWriter(args.log_dir) as writer:
        writer.add_scalar("loss", float(loss), step)
"""

import itertools
import json
import os
import socket
import struct
import time

from tensorflowonspark_tpu import tfrecord
from tensorflowonspark_tpu.example_proto import (
    _write_len_delimited, _write_tag, _write_varint)

_WIRE_VARINT = 0
_WIRE_I64 = 1
_WIRE_I32 = 5

#: Per-process monotonic counter folded into event filenames: two writers
#: opened in the same process within the same wall-clock second (retry
#: loops, tests) would otherwise produce the SAME path and silently
#: interleave their records into one file.
_FILE_COUNTER = itertools.count()


def _encode_value(tag, simple_value):
    out = bytearray()
    _write_len_delimited(out, 1, tag.encode("utf-8"))
    _write_tag(out, 2, _WIRE_I32)
    out += struct.pack("<f", float(simple_value))
    return bytes(out)


def encode_scalar_event(tag, value, step, wall_time=None):
    """One ``Event{step, wall_time, summary{value{tag, simple_value}}}``."""
    summary = bytearray()
    _write_len_delimited(summary, 1, _encode_value(tag, value))
    out = bytearray()
    _write_tag(out, 1, _WIRE_I64)
    out += struct.pack("<d", time.time() if wall_time is None else wall_time)
    _write_tag(out, 2, _WIRE_VARINT)
    _write_varint(out, int(step))
    _write_len_delimited(out, 5, bytes(summary))
    return bytes(out)


def _encode_text_value(tag, text):
    """A text-plugin ``Summary.Value``: ``metadata.plugin_data.plugin_name =
    "text"`` (field 9 → 1 → 1) plus a rank-1 DT_STRING ``tensor`` (field 8:
    dtype=7, shape dim size 1, ``string_val``) — the public wire shape
    TensorBoard's text dashboard reads."""
    plugin_data = bytearray()
    _write_len_delimited(plugin_data, 1, b"text")
    metadata = bytearray()
    _write_len_delimited(metadata, 1, bytes(plugin_data))
    dim = bytearray()
    _write_tag(dim, 1, _WIRE_VARINT)
    _write_varint(dim, 1)
    shape = bytearray()
    _write_len_delimited(shape, 2, bytes(dim))
    tensor = bytearray()
    _write_tag(tensor, 1, _WIRE_VARINT)
    _write_varint(tensor, 7)  # DT_STRING
    _write_len_delimited(tensor, 2, bytes(shape))
    _write_len_delimited(tensor, 8, text.encode("utf-8"))
    out = bytearray()
    _write_len_delimited(out, 1, tag.encode("utf-8"))
    _write_len_delimited(out, 8, bytes(tensor))
    _write_len_delimited(out, 9, bytes(metadata))
    return bytes(out)


def encode_text_event(tag, text, step, wall_time=None):
    """One ``Event`` carrying a text-plugin summary (markdown-rendered by
    TensorBoard's text dashboard)."""
    summary = bytearray()
    _write_len_delimited(summary, 1, _encode_text_value(tag, text))
    out = bytearray()
    _write_tag(out, 1, _WIRE_I64)
    out += struct.pack("<d", time.time() if wall_time is None else wall_time)
    _write_tag(out, 2, _WIRE_VARINT)
    _write_varint(out, int(step))
    _write_len_delimited(out, 5, bytes(summary))
    return bytes(out)


def encode_file_version_event(wall_time=None):
    """The required first record: ``Event{file_version: "brain.Event:2"}``."""
    out = bytearray()
    _write_tag(out, 1, _WIRE_I64)
    out += struct.pack("<d", time.time() if wall_time is None else wall_time)
    _write_len_delimited(out, 3, b"brain.Event:2")
    return bytes(out)


class SummaryWriter(object):
    """Append-only scalar event writer (one standard tfevents file).

    Open it on the chief only — the convention every reference example
    follows — and point the framework-launched TensorBoard at ``logdir``.
    """

    def __init__(self, logdir, filename_suffix=""):
        # Local filesystem only: strip file://, refuse remote schemes loudly
        # (silently creating a literal './hdfs:/...' dir would hide every
        # curve from the TensorBoard watching the real log_dir).
        if logdir.startswith("file://"):
            logdir = logdir[len("file://"):]
        if "://" in logdir:
            raise ValueError(
                "SummaryWriter writes to the local filesystem; got {!r} "
                "(write locally and sync, or mount the remote store)"
                .format(logdir))
        os.makedirs(logdir, exist_ok=True)
        name = "events.out.tfevents.{:.0f}.{}.{}.{}{}".format(
            time.time(), socket.gethostname(), os.getpid(),
            next(_FILE_COUNTER), filename_suffix)
        self.path = os.path.join(logdir, name)
        self._writer = tfrecord.TFRecordWriter(self.path)
        self._writer.write(encode_file_version_event())
        self.flush()

    def add_scalar(self, tag, value, step, wall_time=None):
        self._writer.write(
            encode_scalar_event(tag, float(value), step, wall_time))

    def add_scalars(self, scalars, step):
        """``{tag: value}`` convenience (one event per tag, same step)."""
        for tag, value in scalars.items():
            self.add_scalar(tag, value, step)

    def add_text(self, tag, text, step=0, wall_time=None):
        """Write a text-plugin event (TensorBoard renders it as markdown)."""
        self._writer.write(encode_text_event(tag, text, step, wall_time))

    def add_run_metadata(self, ctx_or_dict, step=0):
        """Record the run's cluster shape as a step-0 text event, so the
        TensorBoard run carries WHAT produced these curves (cluster size,
        role, host) alongside them.  Pass a node context (its ``job_name``/
        ``task_index``/``num_executors``/``cluster_meta`` are summarized)
        or any JSON-serializable dict."""
        if isinstance(ctx_or_dict, dict):
            info = dict(ctx_or_dict)
        else:
            ctx = ctx_or_dict
            info = {"job_name": getattr(ctx, "job_name", None),
                    "task_index": getattr(ctx, "task_index", None),
                    "executor_id": getattr(ctx, "executor_id", None),
                    "num_executors": getattr(ctx, "num_executors", None),
                    "host": socket.gethostname()}
            meta = getattr(ctx, "cluster_meta", None) or {}
            for key in ("id", "cluster_template", "input_mode"):
                if key in meta:
                    info["cluster_" + key] = meta[key]
        text = "```json\n{}\n```".format(
            json.dumps(info, indent=2, sort_keys=True, default=str))
        self.add_text("run_metadata", text, step=step)

    def flush(self):
        self._writer.flush()

    def close(self):
        self._writer.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
