#!/usr/bin/env bash
# Test harness entry point (reference test/run_tests.sh).
#
# The reference started a real 2-worker Spark Standalone cluster, ran
# `python -m unittest discover`, and tore it down (reference
# test/run_tests.sh:15-22).  Here the equivalents are built into the suite
# itself: tests/conftest.py arms an 8-device virtual CPU mesh, the
# process-backed pyspark shim (tests/sparkshim) provides separate executor
# processes, and tests/test_multiprocess.py spawns real multi-process
# jax.distributed worlds.
#
# Usage:
#   ./run_tests.sh            # full suite (~27 min on 8 CPU cores; 258
#                             # tests incl. all example-CLI integration runs)
#   ./run_tests.sh -m 'not slow'   # fast subset, ~5 min — every framework
#                                  # module; 'slow' marks the example/cluster
#                                  # integration runs (each boots multi-
#                                  # process clusters in subprocesses)
#   ./run_tests.sh tests/test_cluster.py   # one file
set -euo pipefail
cd "$(dirname "$0")"

# capture the exit code without tripping `set -e` (a bare `rc=$?` after a
# failing pytest would never run: -e aborts the script on the failure, and
# the gates below must execute either way)
rc=0
python -m pytest tests/ -q --durations=10 "$@" || rc=$?

# the driver gates: compile-check the graft entry + the multi-chip dry run,
# prove the elastic-recovery loop closes on a real 3-node cluster, prove
# the telemetry plane produces parseable traces + HBEAT counters, prove
# the data service keeps its exactly-once guarantee through a worker
# SIGKILL (dispatcher + 2 worker subprocesses + 2 consumers), prove the
# step loop overlaps: guard-clean device-resident dispatches, async
# checkpoint saves, and dispatch-gap counters reaching the driver, then
# prove the observatory answers live: /metrics + /status scrapeable
# mid-run with the MFU/goodput accountant, counters monotone, and trace
# flow events linking a data-service split to a consumer-side dispatch,
# then prove the device plane explains itself: attribution gauges on
# /metrics summing to ~100%, a mid-run GET /profile collecting every
# node's device trace to the driver, and analyze_profile.py merging them
# with the host traces into one Perfetto timeline, and finally prove the
# watchtower catches an injected straggler and an injected NaN loss live
# (correctly attributed on /alerts, /metrics, /status and as trace
# instants) and that metrics_replay.py re-derives the same alerts from
# the on-disk journal after the cluster is gone, and prove the caching
# tier pays: 2 cache-armed worker subprocesses serving a 2-epoch job with
# >=90% epoch-2 cache hits, compressed colv1 frames, and a nonzero
# wire-compression ratio on a live /metrics scrape, and prove the serving
# gateway survives chaos: 2 replica subprocesses under concurrent client
# load, the pinned replica SIGKILLed mid-run and fenced by heartbeat
# timeout, zero accepted requests lost across the failover, and the
# serving telemetry (nonzero tfos_serving_p99_us / tfos_serving_batch_fill
# plus a live latency_slo_burn alert) on /metrics and /alerts, and prove
# the warm-start compile plane: a SIGKILLed worker's replacement rejoins
# with a deserialized (never retraced) step executable, compile debt a
# small fraction of the cold nodes', exact element totals preserved, and
# nonzero tfos_compile_cache_hit_total on a live /metrics scrape, and
# prove the multi-tenant tier survives chaos: two consumer runs attached
# to ONE shared 2-epoch job, the journaled dispatcher subprocess
# SIGKILLed and restarted mid-run on the same port, exact element totals
# with zero duplicates across the crash, and nonzero
# tfos_dataservice_cache_hit_total plus the affinity hit-rate on a live
# /metrics scrape, and finally prove the autopilot closes the loop live:
# a 2-node cluster with prefetch pinned low gets its depth raised by the
# controller mid-run, the measured starvation wall-fraction drops, every
# action lands in the journal and on /autopilot, and metrics_replay.py
# re-derives the action stream offline, and prove the control plane
# itself survives: the primary reservation server is stalled then
# SIGKILLed mid-run, the warm standby promotes off the journal under a
# bumped fencing epoch, the zombie's writes are rejected by epoch, nodes
# re-home via endpoint-list redial with exact item totals and no healthy
# node false-fenced during the takeover grace window, and prove the
# megastep engine amortizes: a 2-node cluster under
# TFOS_TRANSFER_GUARD=disallow runs guard-clean K=4 grouped dispatches
# with device-side stack assembly and donated stacks, a live
# train_steps_per_call=8 push through node.apply_knobs lands exactly on a
# group boundary (whole-group step deltas, steps_per_call gauge), every
# row trains exactly once, and warm host+dispatch wall per step through
# multi_step(8) is measurably below the single-step path's, and prove
# the remediator closes the detect→act loop: a 3-node cluster with an
# injected straggler and a saturated data plane sees the watchtower
# name both, the remediator evict the straggler (graceful SIGTERM
# drain, slot release, elastic replacement admitted) and scale out a
# feed worker, with exact consumer totals and zero operator input, the
# journal holding the full proposed→applied→effect chain re-derivable
# by metrics_replay.py — then a NaN batch injected mid-train trips the
# nonfinite rule and the remediator rolls back past the poisoned step
# (quarantined .corrupt) to completion — and prove the request plane
# explains itself: two traced replicas (one with an injected 50ms
# dispatch stall) serve four concurrent clients, per-stage latency
# histograms re-add to the e2e sum on /metrics, /slow names the stalled
# requests by client-minted id, slo_budget_burn pages the slow replica
# only, the merged timeline stitches cross-process request flows, and
# metrics_replay.py re-derives the identical verdicts from the journal,
# and finally prove the model fleet holds: a 3-model registry-resolved
# fleet (2 replicas each) under concurrent multi-model clients sees a
# poisoned beta@2 (finite params, overflowing matmuls) canaried onto one
# replica and auto-rolled-back off the version-labeled nonfinite signal,
# then a real fit_supervised run publishes beta@3 through the
# train-to-serve handoff and the canary controller walks it to live on
# every replica — zero accepted requests lost, every answer numerically
# traceable to a published version, serving_compiles flat through both
# swaps (weight flips never recompile), client p99 flat, /fleet serving
# the control-plane state, and fleet.replay_journal re-deriving the
# exact promote/rollback stream from the canary journal
python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"
python scripts/ci_assert_elastic.py
python scripts/ci_assert_telemetry.py
python scripts/ci_assert_dataservice.py
python scripts/ci_assert_cache.py
python scripts/ci_assert_overlap.py
python scripts/ci_assert_observatory.py
python scripts/ci_assert_profiling.py
python scripts/ci_assert_watchtower.py
python scripts/ci_assert_serving.py
python scripts/ci_assert_warmstart.py
python scripts/ci_assert_shared.py
python scripts/ci_assert_autopilot.py
python scripts/ci_assert_ha.py
python scripts/ci_assert_megastep.py
python scripts/ci_assert_remediator.py
python scripts/ci_assert_reqtrace.py
python scripts/ci_assert_fleet.py

exit $rc
