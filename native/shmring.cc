// Shared-memory SPSC ring buffer: the native transport of the feed data
// plane.  The reference's data plane crossed a multiprocessing-manager
// socket per element (reference TFManager.py / TFNode.py:124-149, the
// InputMode.SPARK ceiling); here bulk chunk payloads move through a
// lock-free shared-memory ring between the feed task and the training
// process on the same host, with only tiny ordering tokens left on the
// manager queue (see tensorflowonspark_tpu/shmring.py for the protocol).
//
// Design: single producer, single consumer (the backend schedules feed
// tasks sequentially per executor — one task slot, like the reference,
// TFSparkNode.py:110-115).  Records are [u32 length][payload] packed
// contiguously; a length of 0xFFFFFFFF is a wrap marker telling the reader
// to jump back to offset 0.  head/tail are monotonically increasing byte
// offsets (mod capacity for addressing) in a cache-line-separated header.
// Blocking uses a bounded spin + nanosleep backoff — portable, and the
// ~50us sleep is negligible against multi-KB chunk payloads.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <new>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x54464f53524e4731ULL;  // "TFOSRNG1"
constexpr uint32_t kWrapMarker = 0xFFFFFFFFu;

struct Header {
  uint64_t magic;
  uint64_t capacity;                       // data region bytes
  alignas(64) std::atomic<uint64_t> head;  // bytes written (monotonic)
  alignas(64) std::atomic<uint64_t> tail;  // bytes consumed (monotonic)
  alignas(64) std::atomic<uint64_t> closed;
};

struct Ring {
  Header* hdr;
  uint8_t* data;
  uint64_t capacity;
  size_t map_len;
  bool owner;
  char name[256];
};

void backoff(unsigned spins) {
  if (spins < 64) return;  // busy spin first
  struct timespec ts = {0, 50 * 1000};  // 50us
  nanosleep(&ts, nullptr);
}

uint64_t now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
}

// Block until a contiguous `len`-byte record (plus its u32 length header)
// fits, laying down a wrap marker when the record would straddle the end.
// On success, *head_out is the pre-advance head and *wpos_out the record's
// offset in the data region; the caller writes [len32][payload] there and
// publishes with a release store of head_out + len + 4.  Returns 0, or the
// shmring_write error codes (-1 timeout, -2 closed, -3 can never fit).
int reserve_record(Ring* r, uint64_t len, uint64_t timeout_ms,
                   uint64_t* head_out, uint64_t* wpos_out) {
  Header* h = r->hdr;
  if (len >= kWrapMarker) return -3;  // length header is 32-bit framing
  const uint64_t need = len + 4;
  if (need + 4 > r->capacity) return -3;  // +4: worst-case wrap marker
  const uint64_t deadline = timeout_ms ? now_ms() + timeout_ms : 0;
  uint64_t head = h->head.load(std::memory_order_relaxed);
  unsigned spins = 0;
  for (;;) {
    if (h->closed.load(std::memory_order_acquire)) return -2;
    const uint64_t tail = h->tail.load(std::memory_order_acquire);
    const uint64_t pos = head % r->capacity;
    const uint64_t to_end = r->capacity - pos;
    // Reserve a wrap marker too when the record would straddle the end.
    const uint64_t reserve = (to_end < need) ? to_end + need : need;
    if (reserve > r->capacity) return -3;  // can never fit at THIS offset:
                                           // caller takes the queue fallback
                                           // rather than starving forever
    if (head + reserve - tail <= r->capacity) {
      if (to_end < need) {
        if (to_end >= 4) {
          uint32_t wrap = kWrapMarker;
          memcpy(r->data + pos, &wrap, 4);
        }  // < 4 bytes left: reader detects the short tail itself
        head += to_end;  // jump to start of ring
      }
      *head_out = head;
      *wpos_out = head % r->capacity;
      return 0;
    }
    if (deadline && now_ms() > deadline) return -1;
    backoff(spins++);
  }
}

}  // namespace

extern "C" {

// Create (owner) or attach to the ring named `name` (shm_open name, must
// start with '/').  capacity is rounded up to a page multiple; pass 0 when
// attaching.  Returns an opaque handle or null.
void* shmring_create(const char* name, uint64_t capacity) {
  long page = sysconf(_SC_PAGESIZE);
  capacity = ((capacity + page - 1) / page) * page;
  size_t map_len = sizeof(Header) + capacity;
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(map_len)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  Header* hdr = new (mem) Header();
  hdr->capacity = capacity;
  hdr->head.store(0, std::memory_order_relaxed);
  hdr->tail.store(0, std::memory_order_relaxed);
  hdr->closed.store(0, std::memory_order_relaxed);
  hdr->magic = kMagic;  // last: attachers spin on magic
  Ring* r = new Ring();
  r->hdr = hdr;
  r->data = reinterpret_cast<uint8_t*>(mem) + sizeof(Header);
  r->capacity = capacity;
  r->map_len = map_len;
  r->owner = true;
  strncpy(r->name, name, sizeof(r->name) - 1);
  r->name[sizeof(r->name) - 1] = 0;
  return r;
}

void* shmring_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < (off_t)sizeof(Header)) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Header* hdr = reinterpret_cast<Header*>(mem);
  for (unsigned spins = 0; hdr->magic != kMagic; ++spins) {
    if (spins > 200000) {  // ~10s: creator never finished initializing
      munmap(mem, st.st_size);
      return nullptr;
    }
    backoff(spins | 64);
  }
  Ring* r = new Ring();
  r->hdr = hdr;
  r->data = reinterpret_cast<uint8_t*>(mem) + sizeof(Header);
  r->capacity = hdr->capacity;
  r->map_len = st.st_size;
  r->owner = false;
  strncpy(r->name, name, sizeof(r->name) - 1);
  r->name[sizeof(r->name) - 1] = 0;
  return r;
}

// Write one record.  Returns 0 on success, -1 on timeout, -2 if closed,
// -3 if the record can never fit (len + framing > capacity).
int shmring_write(void* handle, const uint8_t* buf, uint64_t len,
                  uint64_t timeout_ms) {
  Ring* r = static_cast<Ring*>(handle);
  uint64_t head, wpos;
  const int rc = reserve_record(r, len, timeout_ms, &head, &wpos);
  if (rc != 0) return rc;
  uint32_t len32 = static_cast<uint32_t>(len);
  memcpy(r->data + wpos, &len32, 4);
  memcpy(r->data + wpos + 4, buf, len);
  r->hdr->head.store(head + len + 4, std::memory_order_release);
  return 0;
}

// Gather-write ONE record from `nbufs` buffers (the zero-copy columnar
// frame path: header + each column's raw buffer, one memcpy per buffer
// straight into the ring — no intermediate serialization buffer).  Same
// return codes as shmring_write.
int shmring_writev(void* handle, const uint8_t* const* bufs,
                   const uint64_t* lens, uint64_t nbufs,
                   uint64_t timeout_ms) {
  Ring* r = static_cast<Ring*>(handle);
  uint64_t len = 0;
  for (uint64_t i = 0; i < nbufs; ++i) len += lens[i];
  uint64_t head, wpos;
  const int rc = reserve_record(r, len, timeout_ms, &head, &wpos);
  if (rc != 0) return rc;
  uint32_t len32 = static_cast<uint32_t>(len);
  memcpy(r->data + wpos, &len32, 4);
  uint64_t off = wpos + 4;
  for (uint64_t i = 0; i < nbufs; ++i) {
    memcpy(r->data + off, bufs[i], lens[i]);
    off += lens[i];
  }
  r->hdr->head.store(head + len + 4, std::memory_order_release);
  return 0;
}

// Size of the next record: >=0, -1 on timeout, -2 if closed and drained.
int64_t shmring_next_len(void* handle, uint64_t timeout_ms) {
  Ring* r = static_cast<Ring*>(handle);
  Header* h = r->hdr;
  const uint64_t deadline = timeout_ms ? now_ms() + timeout_ms : 0;
  unsigned spins = 0;
  for (;;) {
    uint64_t tail = h->tail.load(std::memory_order_relaxed);
    const uint64_t head = h->head.load(std::memory_order_acquire);
    if (head != tail) {
      uint64_t pos = tail % r->capacity;
      const uint64_t to_end = r->capacity - pos;
      if (to_end < 4) {  // unusable short tail: writer jumped to 0
        h->tail.store(tail + to_end, std::memory_order_release);
        continue;
      }
      uint32_t len32;
      memcpy(&len32, r->data + pos, 4);
      if (len32 == kWrapMarker) {  // explicit wrap marker
        h->tail.store(tail + to_end, std::memory_order_release);
        continue;
      }
      return static_cast<int64_t>(len32);
    }
    if (h->closed.load(std::memory_order_acquire)) return -2;
    if (deadline && now_ms() > deadline) return -1;
    backoff(spins++);
  }
}

// Two-phase zero-copy read, phase 1: block like shmring_next_len, then
// expose a pointer to the next record's payload IN the ring (records never
// straddle the wrap, so the payload is always contiguous).  The record
// stays owned by the ring: the consumer copies what it needs out of *out
// and then calls shmring_consume to release the space — dereferencing the
// pointer after consume races the producer's overwrite.  Returns the
// payload length, -1 on timeout, -2 if closed and drained.
int64_t shmring_peek(void* handle, uint64_t timeout_ms,
                     const uint8_t** out) {
  const int64_t n = shmring_next_len(handle, timeout_ms);
  if (n < 0) return n;
  Ring* r = static_cast<Ring*>(handle);
  const uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  *out = r->data + (tail % r->capacity) + 4;
  return n;
}

// Two-phase zero-copy read, phase 2: advance past the record exposed by
// shmring_peek (shmring_pop without the copy), releasing its bytes back to
// the producer.
void shmring_consume(void* handle) {
  Ring* r = static_cast<Ring*>(handle);
  Header* h = r->hdr;
  const uint64_t tail = h->tail.load(std::memory_order_relaxed);
  uint32_t len32;
  memcpy(&len32, r->data + tail % r->capacity, 4);
  h->tail.store(tail + 4 + len32, std::memory_order_release);
}

// Copy the next record into out (caller sized it via shmring_next_len) and
// advance the tail.  Returns bytes copied.
int64_t shmring_pop(void* handle, uint8_t* out, uint64_t out_len) {
  Ring* r = static_cast<Ring*>(handle);
  Header* h = r->hdr;
  const uint64_t tail = h->tail.load(std::memory_order_relaxed);
  const uint64_t pos = tail % r->capacity;
  uint32_t len32;
  memcpy(&len32, r->data + pos, 4);
  if (len32 > out_len) return -1;
  memcpy(out, r->data + pos + 4, len32);
  h->tail.store(tail + 4 + len32, std::memory_order_release);
  return static_cast<int64_t>(len32);
}

// Bytes currently buffered (approximate; racy by design).
uint64_t shmring_fill(void* handle) {
  Ring* r = static_cast<Ring*>(handle);
  return r->hdr->head.load(std::memory_order_acquire) -
         r->hdr->tail.load(std::memory_order_acquire);
}

void shmring_close(void* handle) {  // producer: no more writes
  static_cast<Ring*>(handle)->hdr->closed.store(1, std::memory_order_release);
}

int shmring_closed(void* handle) {
  return static_cast<Ring*>(handle)->hdr->closed.load(
             std::memory_order_acquire) != 0;
}

void shmring_reopen(void* handle) {  // next feed job resumes writing
  static_cast<Ring*>(handle)->hdr->closed.store(0, std::memory_order_release);
}

// Detach this handle's mapping.  Never unlinks: the object must stay
// attachable for later feed tasks until the cluster explicitly unlinks it
// at shutdown (shmring_unlink) — an implicit owner-unlink here would let a
// subsequent create() produce a second ring under the same name while the
// consumer still reads the first.
void shmring_free(void* handle) {
  Ring* r = static_cast<Ring*>(handle);
  munmap(r->hdr, r->map_len);
  delete r;
}

int shmring_unlink(const char* name) { return shm_unlink(name); }

}  // extern "C"
