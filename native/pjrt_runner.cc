// Standalone C++ PJRT serving runner — the native executor for the export's
// embedded StableHLO artifact (SURVEY §2.3: "StableHLO export + a C++
// xla::PjRtClient runner on TPU hosts", the role libtensorflow-JNI played
// for the reference's JVM serving path, TFModel.scala:245-292).
//
// Loads any PJRT C-API plugin (libtpu.so on TPU hosts; any GetPjrtApi()
// exporter works), compiles a StableHLO module produced by
// `checkpoint.export_model(..., model=..., embed=...)`, feeds raw host
// buffers, executes on device 0, and writes raw output buffers — no Python,
// no flax, no framework on the serving host.
//
// Usage:
//   pjrt_run --plugin /lib/libtpu.so --program apply_embedded.mlir \
//            --options compile_options.pb \
//            --input f32:128,28,28,1:images.bin [--input ...] \
//            [--create_option key=value ...] \
//            --out /tmp/pred
//
// Inputs are dense row-major host buffers; order must match the module's
// flattened argument order (the export descriptor records it).  Each output
// i is written to <out>.<i>.bin and described on stdout as
//   output <i>: type=<t> dims=<d0,d1,...> bytes=<n>
//
// Build (native.py does this on demand):
//   g++ -O3 -std=c++17 -I<tf-include> -o pjrt_run pjrt_runner.cc -ldl

#include <dlfcn.h>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

#include "tensorflow/compiler/xla/pjrt/c/pjrt_c_api.h"

namespace {

[[noreturn]] void Die(const std::string& msg) {
  std::fprintf(stderr, "pjrt_run: %s\n", msg.c_str());
  std::exit(1);
}

// Fatal-on-error checker: serving is a batch CLI, any API error is terminal.
void Check(const PJRT_Api* api, PJRT_Error* err, const char* what) {
  if (err == nullptr) return;
  PJRT_Error_Message_Args margs;
  margs.struct_size = PJRT_Error_Message_Args_STRUCT_SIZE;
  margs.extension_start = nullptr;
  margs.error = err;
  api->PJRT_Error_Message(&margs);
  std::string text(margs.message, margs.message_size);
  PJRT_Error_Destroy_Args dargs;
  dargs.struct_size = PJRT_Error_Destroy_Args_STRUCT_SIZE;
  dargs.extension_start = nullptr;
  dargs.error = err;
  api->PJRT_Error_Destroy(&dargs);
  Die(std::string(what) + ": " + text);
}

void Await(const PJRT_Api* api, PJRT_Event* event, const char* what) {
  if (event == nullptr) return;
  PJRT_Event_Await_Args aargs;
  aargs.struct_size = PJRT_Event_Await_Args_STRUCT_SIZE;
  aargs.extension_start = nullptr;
  aargs.event = event;
  Check(api, api->PJRT_Event_Await(&aargs), what);
  PJRT_Event_Destroy_Args dargs;
  dargs.struct_size = PJRT_Event_Destroy_Args_STRUCT_SIZE;
  dargs.extension_start = nullptr;
  dargs.event = event;
  Check(api, api->PJRT_Event_Destroy(&dargs), "event destroy");
}

std::string ReadFile(const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) Die("cannot read " + path);
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

struct DType {
  PJRT_Buffer_Type type;
  size_t bytes;
};

DType ParseDType(const std::string& s) {
  if (s == "f32") return {PJRT_Buffer_Type_F32, 4};
  if (s == "f64") return {PJRT_Buffer_Type_F64, 8};
  if (s == "f16") return {PJRT_Buffer_Type_F16, 2};
  if (s == "bf16") return {PJRT_Buffer_Type_BF16, 2};
  if (s == "s8") return {PJRT_Buffer_Type_S8, 1};
  if (s == "s16") return {PJRT_Buffer_Type_S16, 2};
  if (s == "s32") return {PJRT_Buffer_Type_S32, 4};
  if (s == "s64") return {PJRT_Buffer_Type_S64, 8};
  if (s == "u8") return {PJRT_Buffer_Type_U8, 1};
  if (s == "u16") return {PJRT_Buffer_Type_U16, 2};
  if (s == "u32") return {PJRT_Buffer_Type_U32, 4};
  if (s == "u64") return {PJRT_Buffer_Type_U64, 8};
  if (s == "pred") return {PJRT_Buffer_Type_PRED, 1};
  Die("unknown dtype " + s + " (use f32/bf16/s32/u8/...)");
}

const char* TypeName(PJRT_Buffer_Type t) {
  switch (t) {
    case PJRT_Buffer_Type_F32: return "f32";
    case PJRT_Buffer_Type_F64: return "f64";
    case PJRT_Buffer_Type_F16: return "f16";
    case PJRT_Buffer_Type_BF16: return "bf16";
    case PJRT_Buffer_Type_S8: return "s8";
    case PJRT_Buffer_Type_S16: return "s16";
    case PJRT_Buffer_Type_S32: return "s32";
    case PJRT_Buffer_Type_S64: return "s64";
    case PJRT_Buffer_Type_U8: return "u8";
    case PJRT_Buffer_Type_U16: return "u16";
    case PJRT_Buffer_Type_U32: return "u32";
    case PJRT_Buffer_Type_U64: return "u64";
    case PJRT_Buffer_Type_PRED: return "pred";
    default: return "other";
  }
}

struct InputSpec {
  DType dtype;
  std::vector<int64_t> dims;
  std::string path;
};

// "f32:128,28,28,1:images.bin" -> InputSpec
InputSpec ParseInput(const std::string& arg) {
  InputSpec spec;
  size_t c1 = arg.find(':');
  size_t c2 = arg.find(':', c1 == std::string::npos ? 0 : c1 + 1);
  if (c1 == std::string::npos || c2 == std::string::npos)
    Die("--input wants dtype:d0,d1,...:path, got " + arg);
  spec.dtype = ParseDType(arg.substr(0, c1));
  std::string dims = arg.substr(c1 + 1, c2 - c1 - 1);
  std::stringstream ds(dims);
  std::string tok;
  while (std::getline(ds, tok, ',')) {
    if (!tok.empty()) spec.dims.push_back(std::stoll(tok));
  }
  spec.path = arg.substr(c2 + 1);
  return spec;
}

// Client-create option, parsed from a repeatable `--create_option key=value`
// flag.  Production plugins reject a bare PJRT_Client_Create: libtpu wants
// ml_framework_name etc., and proxying plugins need their routing options
// (topology, session_id, ...).  Value typing: an explicit `int:`/`str:`/
// `bool:`/`float:` prefix wins; otherwise all-digits (optional sign) is
// kInt64, `true`/`false` is kBool, anything else a string.
struct CreateOption {
  std::string name;
  PJRT_NamedValue_Type type;
  std::string str;       // storage for kString
  int64_t i64 = 0;
  float f32 = 0.0f;
  bool b = false;
};

bool AllDigits(const std::string& s) {
  size_t start = (!s.empty() && (s[0] == '-' || s[0] == '+')) ? 1 : 0;
  if (start >= s.size()) return false;
  for (size_t i = start; i < s.size(); ++i)
    if (s[i] < '0' || s[i] > '9') return false;
  return true;
}

int64_t ParseI64OrDie(const std::string& val, const std::string& arg) {
  try {
    size_t used = 0;
    int64_t v = std::stoll(val, &used);
    if (used != val.size()) throw std::invalid_argument(val);
    return v;
  } catch (const std::exception&) {
    Die("--create_option int value '" + val + "' is not a valid int64 in " +
        arg);
  }
}

float ParseF32OrDie(const std::string& val, const std::string& arg) {
  try {
    size_t used = 0;
    float v = std::stof(val, &used);
    if (used != val.size()) throw std::invalid_argument(val);
    return v;
  } catch (const std::exception&) {
    Die("--create_option float value '" + val + "' is not a valid float in " +
        arg);
  }
}

CreateOption ParseCreateOption(const std::string& arg) {
  size_t eq = arg.find('=');
  if (eq == std::string::npos || eq == 0)
    Die("--create_option wants key=value, got " + arg);
  CreateOption opt;
  opt.name = arg.substr(0, eq);
  std::string val = arg.substr(eq + 1);
  auto strip = [&](const char* prefix) {
    size_t n = std::strlen(prefix);
    if (val.compare(0, n, prefix) == 0) { val = val.substr(n); return true; }
    return false;
  };
  if (strip("str:")) {
    opt.type = PJRT_NamedValue_kString; opt.str = val;
  } else if (strip("int:")) {
    opt.type = PJRT_NamedValue_kInt64; opt.i64 = ParseI64OrDie(val, arg);
  } else if (strip("bool:")) {
    // explicit prefix promises typed parsing: reject anything but the
    // canonical literals rather than coercing "True"/"yes" to false.
    if (val == "true" || val == "1") { opt.b = true; }
    else if (val == "false" || val == "0") { opt.b = false; }
    else Die("--create_option bool value '" + val +
             "' must be true/false/1/0 in " + arg);
    opt.type = PJRT_NamedValue_kBool;
  } else if (strip("float:")) {
    opt.type = PJRT_NamedValue_kFloat; opt.f32 = ParseF32OrDie(val, arg);
  } else if (AllDigits(val)) {
    opt.type = PJRT_NamedValue_kInt64; opt.i64 = ParseI64OrDie(val, arg);
  } else if (val == "true" || val == "false") {
    opt.type = PJRT_NamedValue_kBool; opt.b = (val == "true");
  } else {
    opt.type = PJRT_NamedValue_kString; opt.str = val;
  }
  return opt;
}

// Build the PJRT_NamedValue array over stable CreateOption storage.
std::vector<PJRT_NamedValue> ToNamedValues(
    const std::vector<CreateOption>& opts) {
  std::vector<PJRT_NamedValue> nvs;
  nvs.reserve(opts.size());
  for (const CreateOption& o : opts) {
    PJRT_NamedValue nv;
    std::memset(&nv, 0, sizeof(nv));
    nv.struct_size = PJRT_NamedValue_STRUCT_SIZE;
    nv.extension_start = nullptr;
    nv.name = o.name.c_str();
    nv.name_size = o.name.size();
    nv.type = o.type;
    switch (o.type) {
      case PJRT_NamedValue_kString:
        nv.string_value = o.str.c_str();
        nv.value_size = o.str.size();
        break;
      case PJRT_NamedValue_kInt64:
        nv.int64_value = o.i64;
        nv.value_size = 1;
        break;
      case PJRT_NamedValue_kFloat:
        nv.float_value = o.f32;
        nv.value_size = 1;
        break;
      case PJRT_NamedValue_kBool:
        nv.bool_value = o.b;
        nv.value_size = 1;
        break;
      default:
        Die("unsupported create-option type");
    }
    nvs.push_back(nv);
  }
  return nvs;
}

}  // namespace

int main(int argc, char** argv) {
  std::string plugin_path, program_path, options_path, out_prefix = "out";
  std::vector<InputSpec> inputs;
  std::vector<CreateOption> create_opts;
  // --batches N: each --input file carries N concatenated buffers of the
  // declared shape; the module compiles ONCE and executes N times (the
  // whole point of a serving runner — compilation is minutes on TPU,
  // execution is milliseconds).  Outputs: out.<b>.<i>.bin when N > 1,
  // the original out.<i>.bin when N == 1.
  size_t batches = 1;
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto next = [&](const char* flag) -> std::string {
      if (i + 1 >= argc) Die(std::string(flag) + " needs a value");
      return argv[++i];
    };
    if (a == "--plugin") plugin_path = next("--plugin");
    else if (a == "--program") program_path = next("--program");
    else if (a == "--options") options_path = next("--options");
    else if (a == "--create_option")
      create_opts.push_back(ParseCreateOption(next("--create_option")));
    else if (a == "--input") inputs.push_back(ParseInput(next("--input")));
    else if (a == "--out") out_prefix = next("--out");
    else if (a == "--batches") {
      batches = static_cast<size_t>(std::stoul(next("--batches")));
      if (batches == 0) Die("--batches must be >= 1");
    }
    else Die("unknown flag " + a);
  }
  if (plugin_path.empty() || program_path.empty())
    Die("--plugin and --program are required");

  // 1. Load the plugin and fetch its API table.
  void* handle = dlopen(plugin_path.c_str(), RTLD_NOW | RTLD_LOCAL);
  if (!handle) Die(std::string("dlopen failed: ") + dlerror());
  using GetApiFn = const PJRT_Api* (*)();
  auto get_api = reinterpret_cast<GetApiFn>(dlsym(handle, "GetPjrtApi"));
  if (!get_api) Die("plugin exports no GetPjrtApi symbol");
  const PJRT_Api* api = get_api();
  if (!api) Die("GetPjrtApi returned null");

  PJRT_Plugin_Initialize_Args init_args;
  init_args.struct_size = PJRT_Plugin_Initialize_Args_STRUCT_SIZE;
  init_args.extension_start = nullptr;
  Check(api, api->PJRT_Plugin_Initialize(&init_args), "plugin init");

  // 2. Create the client and pick device 0.
  std::vector<PJRT_NamedValue> nvs = ToNamedValues(create_opts);
  PJRT_Client_Create_Args cargs;
  std::memset(&cargs, 0, sizeof(cargs));
  cargs.struct_size = PJRT_Client_Create_Args_STRUCT_SIZE;
  cargs.create_options = nvs.empty() ? nullptr : nvs.data();
  cargs.num_options = nvs.size();
  Check(api, api->PJRT_Client_Create(&cargs), "client create");
  PJRT_Client* client = cargs.client;

  PJRT_Client_AddressableDevices_Args dargs;
  dargs.struct_size = PJRT_Client_AddressableDevices_Args_STRUCT_SIZE;
  dargs.extension_start = nullptr;
  dargs.client = client;
  Check(api, api->PJRT_Client_AddressableDevices(&dargs), "devices");
  if (dargs.num_addressable_devices == 0) Die("no addressable devices");
  PJRT_Device* device = dargs.addressable_devices[0];

  // 3. Compile the StableHLO module.
  std::string code = ReadFile(program_path);
  std::string options =
      options_path.empty() ? std::string() : ReadFile(options_path);
  PJRT_Program program;
  program.struct_size = PJRT_Program_STRUCT_SIZE;
  program.extension_start = nullptr;
  program.code = code.data();
  program.code_size = code.size();
  static const char kFormat[] = "mlir";
  program.format = kFormat;
  program.format_size = sizeof(kFormat) - 1;

  PJRT_Client_Compile_Args comp;
  comp.struct_size = PJRT_Client_Compile_Args_STRUCT_SIZE;
  comp.extension_start = nullptr;
  comp.client = client;
  comp.program = &program;
  comp.compile_options = options.data();
  comp.compile_options_size = options.size();
  Check(api, api->PJRT_Client_Compile(&comp), "compile");
  PJRT_LoadedExecutable* exec = comp.executable;

  // 4. Read the input files once; each holds `batches` concatenated
  // buffers of the declared per-batch shape.
  std::vector<std::string> host_data(inputs.size());
  std::vector<size_t> batch_bytes(inputs.size());
  for (size_t i = 0; i < inputs.size(); ++i) {
    const InputSpec& spec = inputs[i];
    host_data[i] = ReadFile(spec.path);
    size_t want = spec.dtype.bytes;
    for (int64_t d : spec.dims) want *= static_cast<size_t>(d);
    batch_bytes[i] = want;
    if (host_data[i].size() != want * batches) {
      std::ostringstream ss;
      ss << "input " << i << " (" << spec.path << "): file has "
         << host_data[i].size() << " bytes, dims need " << want << " x "
         << batches << " batches";
      Die(ss.str());
    }
  }

  // 5. Execute (single device).
  PJRT_Executable_NumOutputs_Args nargs;
  nargs.struct_size = PJRT_Executable_NumOutputs_Args_STRUCT_SIZE;
  nargs.extension_start = nullptr;
  PJRT_LoadedExecutable_GetExecutable_Args geargs;
  geargs.struct_size = PJRT_LoadedExecutable_GetExecutable_Args_STRUCT_SIZE;
  geargs.extension_start = nullptr;
  geargs.loaded_executable = exec;
  Check(api, api->PJRT_LoadedExecutable_GetExecutable(&geargs), "get exec");
  nargs.executable = geargs.executable;
  Check(api, api->PJRT_Executable_NumOutputs(&nargs), "num outputs");
  size_t num_outputs = nargs.num_outputs;

  for (size_t b = 0; b < batches; ++b) {
    // stage this batch's slice of every input
    std::vector<PJRT_Buffer*> arg_buffers(inputs.size());
    for (size_t i = 0; i < inputs.size(); ++i) {
      const InputSpec& spec = inputs[i];
      PJRT_Client_BufferFromHostBuffer_Args bargs;
      std::memset(&bargs, 0, sizeof(bargs));
      bargs.struct_size = PJRT_Client_BufferFromHostBuffer_Args_STRUCT_SIZE;
      bargs.client = client;
      bargs.data = host_data[i].data() + b * batch_bytes[i];
      bargs.type = spec.dtype.type;
      bargs.dims = spec.dims.data();
      bargs.num_dims = spec.dims.size();
      bargs.host_buffer_semantics =
          PJRT_HostBufferSemantics_kImmutableUntilTransferCompletes;
      bargs.device = device;
      Check(api, api->PJRT_Client_BufferFromHostBuffer(&bargs), "h2d");
      Await(api, bargs.done_with_host_buffer, "h2d done");
      arg_buffers[i] = bargs.buffer;
    }

    std::vector<PJRT_Buffer*> out_row(num_outputs, nullptr);
    PJRT_Buffer** out_lists[1] = {out_row.data()};
    PJRT_Buffer* const* arg_lists[1] = {arg_buffers.data()};
    PJRT_Event* done_events[1] = {nullptr};

    PJRT_ExecuteOptions opts;
    std::memset(&opts, 0, sizeof(opts));
    opts.struct_size = PJRT_ExecuteOptions_STRUCT_SIZE;

    PJRT_LoadedExecutable_Execute_Args eargs;
    std::memset(&eargs, 0, sizeof(eargs));
    eargs.struct_size = PJRT_LoadedExecutable_Execute_Args_STRUCT_SIZE;
    eargs.executable = exec;
    eargs.options = &opts;
    eargs.argument_lists = arg_lists;
    eargs.num_devices = 1;
    eargs.num_args = arg_buffers.size();
    eargs.output_lists = out_lists;
    eargs.device_complete_events = done_events;
    Check(api, api->PJRT_LoadedExecutable_Execute(&eargs), "execute");
    Await(api, done_events[0], "execute done");

    // copy every output back; <out>.<i>.bin (one batch, back-compat) or
    // <out>.<b>.<i>.bin (batched)
    for (size_t i = 0; i < num_outputs; ++i) {
      PJRT_Buffer* buf = out_row[i];

      PJRT_Buffer_ElementType_Args targs;
      targs.struct_size = PJRT_Buffer_ElementType_Args_STRUCT_SIZE;
      targs.extension_start = nullptr;
      targs.buffer = buf;
      Check(api, api->PJRT_Buffer_ElementType(&targs), "output dtype");

      PJRT_Buffer_Dimensions_Args dims_args;
      dims_args.struct_size = PJRT_Buffer_Dimensions_Args_STRUCT_SIZE;
      dims_args.extension_start = nullptr;
      dims_args.buffer = buf;
      Check(api, api->PJRT_Buffer_Dimensions(&dims_args), "output dims");

      PJRT_Buffer_ToHostBuffer_Args hargs;
      std::memset(&hargs, 0, sizeof(hargs));
      hargs.struct_size = PJRT_Buffer_ToHostBuffer_Args_STRUCT_SIZE;
      hargs.src = buf;
      Check(api, api->PJRT_Buffer_ToHostBuffer(&hargs), "d2h size");
      std::string out(hargs.dst_size, '\0');
      hargs.dst = out.data();
      Check(api, api->PJRT_Buffer_ToHostBuffer(&hargs), "d2h");
      Await(api, hargs.event, "d2h done");

      std::string path = batches == 1
          ? out_prefix + "." + std::to_string(i) + ".bin"
          : out_prefix + "." + std::to_string(b) + "." +
                std::to_string(i) + ".bin";
      std::ofstream f(path, std::ios::binary);
      f.write(out.data(), static_cast<std::streamsize>(out.size()));
      if (!f) Die("cannot write " + path);

      std::ostringstream dimstr;
      for (size_t d = 0; d < dims_args.num_dims; ++d) {
        if (d) dimstr << ",";
        dimstr << dims_args.dims[d];
      }
      std::printf("output %zu.%zu: type=%s dims=%s bytes=%zu file=%s\n", b,
                  i, TypeName(targs.type), dimstr.str().c_str(), out.size(),
                  path.c_str());

      PJRT_Buffer_Destroy_Args bd;
      bd.struct_size = PJRT_Buffer_Destroy_Args_STRUCT_SIZE;
      bd.extension_start = nullptr;
      bd.buffer = buf;
      Check(api, api->PJRT_Buffer_Destroy(&bd), "output destroy");
    }

    for (PJRT_Buffer* buf : arg_buffers) {
      PJRT_Buffer_Destroy_Args bd;
      bd.struct_size = PJRT_Buffer_Destroy_Args_STRUCT_SIZE;
      bd.extension_start = nullptr;
      bd.buffer = buf;
      Check(api, api->PJRT_Buffer_Destroy(&bd), "arg destroy");
    }
  }
  PJRT_LoadedExecutable_Destroy_Args ed;
  ed.struct_size = PJRT_LoadedExecutable_Destroy_Args_STRUCT_SIZE;
  ed.extension_start = nullptr;
  ed.executable = exec;
  Check(api, api->PJRT_LoadedExecutable_Destroy(&ed), "exec destroy");
  PJRT_Client_Destroy_Args cd;
  cd.struct_size = PJRT_Client_Destroy_Args_STRUCT_SIZE;
  cd.extension_start = nullptr;
  cd.client = client;
  Check(api, api->PJRT_Client_Destroy(&cd), "client destroy");
  return 0;
}
