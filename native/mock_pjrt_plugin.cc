// Mock PJRT plugin — a test double exporting GetPjrtApi() so the native
// serving runner (pjrt_runner.cc) can EXECUTE everywhere, not just compile
// (VERDICT r3 item 4: the C++ execute path had never run; no real CPU PJRT
// plugin ships in this image and a TPU plugin needs hardware).
//
// Implements exactly the C-API subset the runner drives — error/event
// plumbing, client + device enumeration, compile, host<->device buffers,
// execute — with deterministic test-double semantics the test can assert:
//
// - compile: dumps the received program bytes to $TFOS_MOCK_PROGRAM_DUMP
//   (so the test can verify the exported StableHLO reached the plugin
//   intact) and reads the output signature from $TFOS_MOCK_OUTPUTS
//   ("f32:4;f32:4,4" = two outputs, shapes (4,) and (4,4)).
// - execute: every output element = (sum of all staged argument bytes
//   modulo 1000003) + output_index, as f32/s32.  The checksum covers the
//   exact bytes the runner staged for THIS batch, so a --batches slicing
//   bug or an argument-marshalling bug changes the value.
//
// Build: g++ -O3 -std=c++17 -shared -fPIC -I<tf-include> \
//            -o libmock_pjrt_plugin.so mock_pjrt_plugin.cc

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

#include "tensorflow/compiler/xla/pjrt/c/pjrt_c_api.h"

// Opaque API types get concrete test-double definitions here (the header
// only forward-declares them).
struct PJRT_Error {
  std::string message;
};
struct PJRT_Event {};  // every mock event is born ready
struct PJRT_Device {
  int id;
};
struct PJRT_Client {
  PJRT_Device device{0};
  PJRT_Device* devices[1];
};
struct PJRT_Buffer {
  PJRT_Buffer_Type type;
  std::vector<int64_t> dims;
  std::string data;
};
struct OutputSpec {
  PJRT_Buffer_Type type;
  size_t elem_bytes;
  std::vector<int64_t> dims;
};
struct PJRT_Executable {
  std::vector<OutputSpec> outputs;
};
struct PJRT_LoadedExecutable {
  PJRT_Executable exec;
};
struct PJRT_TopologyDescription {};

namespace {

PJRT_Error* Err(const std::string& msg) { return new PJRT_Error{msg}; }

PJRT_Error* ErrorMessage(PJRT_Error_Message_Args* args) {
  args->message = args->error->message.c_str();
  args->message_size = args->error->message.size();
  return nullptr;
}

void ErrorDestroy(PJRT_Error_Destroy_Args* args) { delete args->error; }

PJRT_Error* ErrorCode(PJRT_Error_GetCode_Args* args) {
  args->code = PJRT_Error_Code_INTERNAL;
  return nullptr;
}

PJRT_Error* EventAwait(PJRT_Event_Await_Args*) { return nullptr; }
PJRT_Error* EventDestroy(PJRT_Event_Destroy_Args* args) {
  delete args->event;
  return nullptr;
}
PJRT_Error* EventIsReady(PJRT_Event_IsReady_Args* args) {
  args->is_ready = true;
  return nullptr;
}

PJRT_Error* PluginInitialize(PJRT_Plugin_Initialize_Args*) { return nullptr; }

PJRT_Error* ClientCreate(PJRT_Client_Create_Args* args) {
  // When $TFOS_MOCK_OPTIONS_DUMP is set, record the NamedValue create
  // options the caller passed, one `name=typed-value` line each — lets the
  // suite assert the runner's --create_option marshalling end-to-end
  // (real plugins REQUIRE such options; axon rejects a bare create).
  const char* odump = std::getenv("TFOS_MOCK_OPTIONS_DUMP");
  if (odump != nullptr) {
    std::ofstream f(odump);
    for (size_t i = 0; i < args->num_options; ++i) {
      const PJRT_NamedValue& nv = args->create_options[i];
      f << std::string(nv.name, nv.name_size) << "=";
      switch (nv.type) {
        case PJRT_NamedValue_kString:
          f << "str:" << std::string(nv.string_value, nv.value_size); break;
        case PJRT_NamedValue_kInt64: f << "int:" << nv.int64_value; break;
        case PJRT_NamedValue_kFloat: f << "float:" << nv.float_value; break;
        case PJRT_NamedValue_kBool:
          f << "bool:" << (nv.bool_value ? "true" : "false"); break;
        default: f << "other"; break;
      }
      f << "\n";
    }
  }
  auto* client = new PJRT_Client;
  client->devices[0] = &client->device;
  args->client = client;
  return nullptr;
}

PJRT_Error* ClientDestroy(PJRT_Client_Destroy_Args* args) {
  delete args->client;
  return nullptr;
}

PJRT_Error* AddressableDevices(PJRT_Client_AddressableDevices_Args* args) {
  args->addressable_devices = args->client->devices;
  args->num_addressable_devices = 1;
  return nullptr;
}

// "f32:4;f32:4,4" -> OutputSpecs
PJRT_Error* ParseOutputs(const char* spec, std::vector<OutputSpec>* out) {
  std::stringstream ss(spec);
  std::string item;
  while (std::getline(ss, item, ';')) {
    if (item.empty()) continue;
    size_t colon = item.find(':');
    if (colon == std::string::npos)
      return Err("TFOS_MOCK_OUTPUTS wants dtype:d0,d1;... got " + item);
    std::string ty = item.substr(0, colon);
    OutputSpec os;
    if (ty == "f32") {
      os.type = PJRT_Buffer_Type_F32;
      os.elem_bytes = 4;
    } else if (ty == "s32") {
      os.type = PJRT_Buffer_Type_S32;
      os.elem_bytes = 4;
    } else {
      return Err("mock supports f32/s32 outputs, got " + ty);
    }
    std::stringstream ds(item.substr(colon + 1));
    std::string tok;
    while (std::getline(ds, tok, ',')) {
      if (tok.empty()) continue;
      // report malformed dims as a PJRT_Error, never an exception across
      // the C-API boundary (which would abort the runner process)
      try {
        size_t used = 0;
        int64_t dim = std::stoll(tok, &used);
        if (used != tok.size()) throw std::invalid_argument(tok);
        os.dims.push_back(dim);
      } catch (const std::exception&) {
        return Err("TFOS_MOCK_OUTPUTS has non-numeric dim " + tok + " in " +
                   item);
      }
    }
    out->push_back(os);
  }
  if (out->empty()) return Err("TFOS_MOCK_OUTPUTS parsed to zero outputs");
  return nullptr;
}

PJRT_Error* ClientCompile(PJRT_Client_Compile_Args* args) {
  const char* dump = std::getenv("TFOS_MOCK_PROGRAM_DUMP");
  if (dump != nullptr && *dump != '\0') {
    std::ofstream f(dump, std::ios::binary);
    f.write(args->program->code,
            static_cast<std::streamsize>(args->program->code_size));
    if (!f) return Err(std::string("cannot dump program to ") + dump);
  }
  const char* spec = std::getenv("TFOS_MOCK_OUTPUTS");
  if (spec == nullptr || *spec == '\0')
    return Err("TFOS_MOCK_OUTPUTS not set (mock plugin needs the output "
               "signature)");
  auto* loaded = new PJRT_LoadedExecutable;
  if (PJRT_Error* e = ParseOutputs(spec, &loaded->exec.outputs)) {
    delete loaded;
    return e;
  }
  args->executable = loaded;
  return nullptr;
}

PJRT_Error* GetExecutable(PJRT_LoadedExecutable_GetExecutable_Args* args) {
  args->executable = &args->loaded_executable->exec;
  return nullptr;
}

PJRT_Error* NumOutputs(PJRT_Executable_NumOutputs_Args* args) {
  args->num_outputs = args->executable->outputs.size();
  return nullptr;
}

PJRT_Error* LoadedExecutableDestroy(
    PJRT_LoadedExecutable_Destroy_Args* args) {
  delete args->executable;
  return nullptr;
}

PJRT_Error* BufferFromHostBuffer(
    PJRT_Client_BufferFromHostBuffer_Args* args) {
  if (args->num_byte_strides != 0)
    return Err("mock plugin only supports dense row-major host buffers");
  auto* buf = new PJRT_Buffer;
  buf->type = args->type;
  buf->dims.assign(args->dims, args->dims + args->num_dims);
  size_t elem = 1;
  switch (args->type) {
    case PJRT_Buffer_Type_F64:
    case PJRT_Buffer_Type_S64:
    case PJRT_Buffer_Type_U64:
      elem = 8;
      break;
    case PJRT_Buffer_Type_F32:
    case PJRT_Buffer_Type_S32:
    case PJRT_Buffer_Type_U32:
      elem = 4;
      break;
    case PJRT_Buffer_Type_F16:
    case PJRT_Buffer_Type_BF16:
    case PJRT_Buffer_Type_S16:
    case PJRT_Buffer_Type_U16:
      elem = 2;
      break;
    default:
      elem = 1;
  }
  size_t total = elem;
  for (int64_t d : buf->dims) total *= static_cast<size_t>(d);
  buf->data.assign(static_cast<const char*>(args->data), total);
  args->buffer = buf;
  args->done_with_host_buffer = new PJRT_Event;
  return nullptr;
}

PJRT_Error* BufferElementType(PJRT_Buffer_ElementType_Args* args) {
  args->type = args->buffer->type;
  return nullptr;
}

PJRT_Error* BufferDimensions(PJRT_Buffer_Dimensions_Args* args) {
  args->dims = args->buffer->dims.data();
  args->num_dims = args->buffer->dims.size();
  return nullptr;
}

PJRT_Error* BufferToHostBuffer(PJRT_Buffer_ToHostBuffer_Args* args) {
  if (args->dst == nullptr) {
    args->dst_size = args->src->data.size();
    return nullptr;
  }
  if (args->dst_size < args->src->data.size())
    return Err("dst too small");
  std::memcpy(args->dst, args->src->data.data(), args->src->data.size());
  args->event = new PJRT_Event;
  return nullptr;
}

PJRT_Error* BufferDestroy(PJRT_Buffer_Destroy_Args* args) {
  delete args->buffer;
  return nullptr;
}

PJRT_Error* Execute(PJRT_LoadedExecutable_Execute_Args* args) {
  if (args->num_devices != 1) return Err("mock plugin is single-device");
  // checksum over the exact bytes staged for this execution
  uint64_t sum = 0;
  for (size_t a = 0; a < args->num_args; ++a) {
    const std::string& d = args->argument_lists[0][a]->data;
    for (unsigned char c : d) sum += c;
  }
  sum %= 1000003;
  const auto& outs = args->executable->exec.outputs;
  for (size_t i = 0; i < outs.size(); ++i) {
    const OutputSpec& spec = outs[i];
    auto* buf = new PJRT_Buffer;
    buf->type = spec.type;
    buf->dims = spec.dims;
    size_t n = 1;
    for (int64_t d : spec.dims) n *= static_cast<size_t>(d);
    buf->data.resize(n * spec.elem_bytes);
    double value = static_cast<double>(sum % 1000) + static_cast<double>(i);
    for (size_t e = 0; e < n; ++e) {
      if (spec.type == PJRT_Buffer_Type_F32) {
        float v = static_cast<float>(value);
        std::memcpy(&buf->data[e * 4], &v, 4);
      } else {
        int32_t v = static_cast<int32_t>(value);
        std::memcpy(&buf->data[e * 4], &v, 4);
      }
    }
    args->output_lists[0][i] = buf;
  }
  if (args->device_complete_events != nullptr)
    args->device_complete_events[0] = new PJRT_Event;
  return nullptr;
}

PJRT_Api* BuildApi() {
  static PJRT_Api api;
  std::memset(&api, 0, sizeof(api));
  api.struct_size = PJRT_Api_STRUCT_SIZE;
  api.pjrt_api_version.major_version = PJRT_API_MAJOR;
  api.pjrt_api_version.minor_version = PJRT_API_MINOR;
  api.PJRT_Error_Destroy = +[](PJRT_Error_Destroy_Args* a) {
    ErrorDestroy(a);
  };
  api.PJRT_Error_Message = +[](PJRT_Error_Message_Args* a) {
    ErrorMessage(a);
  };
  api.PJRT_Error_GetCode = ErrorCode;
  api.PJRT_Plugin_Initialize = PluginInitialize;
  api.PJRT_Event_Destroy = EventDestroy;
  api.PJRT_Event_IsReady = EventIsReady;
  api.PJRT_Event_Await = EventAwait;
  api.PJRT_Client_Create = ClientCreate;
  api.PJRT_Client_Destroy = ClientDestroy;
  api.PJRT_Client_AddressableDevices = AddressableDevices;
  api.PJRT_Client_Compile = ClientCompile;
  api.PJRT_Client_BufferFromHostBuffer = BufferFromHostBuffer;
  api.PJRT_LoadedExecutable_Destroy = LoadedExecutableDestroy;
  api.PJRT_LoadedExecutable_GetExecutable = GetExecutable;
  api.PJRT_Executable_NumOutputs = NumOutputs;
  api.PJRT_LoadedExecutable_Execute = Execute;
  api.PJRT_Buffer_ElementType = BufferElementType;
  api.PJRT_Buffer_Dimensions = BufferDimensions;
  api.PJRT_Buffer_ToHostBuffer = BufferToHostBuffer;
  api.PJRT_Buffer_Destroy = BufferDestroy;
  return &api;
}

}  // namespace

extern "C" const PJRT_Api* GetPjrtApi() { return BuildApi(); }
