// TFRecord codec: first-party C++ replacement for the reference's bundled
// tensorflow-hadoop jar (reference dfutil.py:39-41, DFUtil.scala:37-40 use
// Java TFRecordFileInput/OutputFormat from lib/tensorflow-hadoop-*.jar).
//
// Record framing (the TFRecord wire format):
//   uint64 length (little-endian)
//   uint32 masked_crc32c(length bytes)
//   byte   data[length]
//   uint32 masked_crc32c(data)
//
// Exposed as a small extern "C" API consumed via ctypes
// (tensorflowonspark_tpu/tfrecord.py); no JVM, no TF runtime.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli, reflected poly 0x82F63B78), slice-by-8 for speed.
// ---------------------------------------------------------------------------

uint32_t g_tables[8][256];
std::once_flag g_tables_once;

// call_once: ctypes calls release the GIL, so concurrent first-use from two
// Python threads must not race the table build.
void init_tables() {
  std::call_once(g_tables_once, [] {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t crc = i;
      for (int k = 0; k < 8; k++)
        crc = (crc >> 1) ^ ((crc & 1) ? 0x82F63B78u : 0);
      g_tables[0][i] = crc;
    }
    for (uint32_t i = 0; i < 256; i++)
      for (int t = 1; t < 8; t++)
        g_tables[t][i] =
            (g_tables[t - 1][i] >> 8) ^ g_tables[0][g_tables[t - 1][i] & 0xFF];
  });
}

uint32_t crc32c(const uint8_t* data, size_t n) {
  init_tables();
  uint32_t crc = 0xFFFFFFFFu;
  while (n >= 8) {
    uint64_t word;
    memcpy(&word, data, 8);
    word ^= crc;  // little-endian host assumed (x86/ARM TPU hosts)
    crc = g_tables[7][word & 0xFF] ^ g_tables[6][(word >> 8) & 0xFF] ^
          g_tables[5][(word >> 16) & 0xFF] ^ g_tables[4][(word >> 24) & 0xFF] ^
          g_tables[3][(word >> 32) & 0xFF] ^ g_tables[2][(word >> 40) & 0xFF] ^
          g_tables[1][(word >> 48) & 0xFF] ^ g_tables[0][(word >> 56) & 0xFF];
    data += 8;
    n -= 8;
  }
  while (n--) crc = (crc >> 8) ^ g_tables[0][(crc ^ *data++) & 0xFF];
  return crc ^ 0xFFFFFFFFu;
}

const uint32_t kMaskDelta = 0xa282ead8u;

uint32_t masked_crc(const uint8_t* data, size_t n) {
  uint32_t crc = crc32c(data, n);
  return ((crc >> 15) | (crc << 17)) + kMaskDelta;
}

struct Writer {
  FILE* f;
};

struct Reader {
  FILE* f;
  std::vector<uint8_t> buf;
};

}  // namespace

extern "C" {

// crc32c of a buffer — exported so Python can share one implementation.
uint32_t tfr_crc32c(const uint8_t* data, uint64_t n) { return crc32c(data, n); }
uint32_t tfr_masked_crc32c(const uint8_t* data, uint64_t n) {
  return masked_crc(data, n);
}

// -- writer -----------------------------------------------------------------

void* tfr_writer_open(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  Writer* w = new Writer{f};
  return w;
}

// returns 0 on success, nonzero on I/O error
int tfr_write(void* handle, const uint8_t* data, uint64_t len) {
  Writer* w = static_cast<Writer*>(handle);
  uint64_t len_le = len;  // little-endian host
  uint32_t len_crc = masked_crc(reinterpret_cast<uint8_t*>(&len_le), 8);
  uint32_t data_crc = masked_crc(data, len);
  if (fwrite(&len_le, 8, 1, w->f) != 1) return 1;
  if (fwrite(&len_crc, 4, 1, w->f) != 1) return 1;
  if (len && fwrite(data, 1, len, w->f) != len) return 1;
  if (fwrite(&data_crc, 4, 1, w->f) != 1) return 1;
  return 0;
}

int tfr_writer_flush(void* handle) {
  return fflush(static_cast<Writer*>(handle)->f);
}

int tfr_writer_close(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  int rc = fclose(w->f);
  delete w;
  return rc;
}

// -- reader -----------------------------------------------------------------

void* tfr_reader_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  Reader* r = new Reader{f, {}};
  return r;
}

// Reads the next record into an internal buffer (valid until the next call).
// Returns the record length, -1 at clean EOF, -2 on corruption/IO error.
int64_t tfr_read_next(void* handle, const uint8_t** out) {
  Reader* r = static_cast<Reader*>(handle);
  uint64_t len;
  size_t got = fread(&len, 1, 8, r->f);
  if (got == 0) return -1;  // clean EOF
  if (got != 8) return -2;
  uint32_t len_crc;
  if (fread(&len_crc, 4, 1, r->f) != 1) return -2;
  if (masked_crc(reinterpret_cast<uint8_t*>(&len), 8) != len_crc) return -2;
  if (len > (1ull << 40)) return -2;  // sanity bound
  r->buf.resize(len);
  if (len && fread(r->buf.data(), 1, len, r->f) != len) return -2;
  uint32_t data_crc;
  if (fread(&data_crc, 4, 1, r->f) != 1) return -2;
  if (masked_crc(r->buf.data(), len) != data_crc) return -2;
  *out = r->buf.data();
  return static_cast<int64_t>(len);
}

int tfr_reader_close(void* handle) {
  Reader* r = static_cast<Reader*>(handle);
  int rc = fclose(r->f);
  delete r;
  return rc;
}

}  // extern "C"
